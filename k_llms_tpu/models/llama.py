"""Pure-functional Llama-family transformer (GQA + RoPE + RMSNorm + SwiGLU).

TPU-first design notes:
- Parameters are a pytree with all layers STACKED on a leading axis and the
  layer stack applied with ``lax.scan`` — one traced block regardless of depth,
  so XLA compiles fast and fuses identically for 2 or 32 layers.
- All matmuls are laid out (tokens, features) x (features, features') so they
  tile straight onto the MXU; bf16 weights/activations, f32 norm/softmax
  accumulation.
- KV caches are preallocated [L, B, S, KVH, D] and updated with
  ``lax.dynamic_update_slice_in_dim`` — static shapes, no data-dependent
  control flow, jit-stable across decode steps.
- The decode path supports a SHARED-PREFIX cache: the prompt (identical across
  the n consensus samples) is prefilled once at batch=1 and every sample
  attends to it broadcast, so prompt KV is stored once instead of n times —
  the HBM win that lets n=32 consensus fit on one chip.

This file replaces the reference's model layer, which is the remote OpenAI API
(`/root/reference/k_llms/resources/completions/completions.py:73`).
"""

from __future__ import annotations

import math
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .config import ModelConfig
from .quant import qdot, qeinsum

Params = Dict[str, Any]


class KVCache(NamedTuple):
    """Stacked per-layer cache: k/v are [num_layers, batch, max_len, kv_heads, head_dim]."""

    k: jax.Array
    v: jax.Array

    @property
    def max_len(self) -> int:
        return self.k.shape[2]


def init_cache(config: ModelConfig, batch: int, max_len: int, dtype=None) -> KVCache:
    dtype = dtype or config.jax_dtype
    shape = (config.num_layers, batch, max_len, config.num_kv_heads, config.head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


# ---------------------------------------------------------------------------
# Parameter init
# ---------------------------------------------------------------------------

def init_params(config: ModelConfig, key: jax.Array, dtype=None) -> Params:
    """Random (scaled-normal) initialization; real checkpoints come from
    k_llms_tpu.models.loader."""
    dtype = dtype or config.jax_dtype
    H, I, V = config.hidden_size, config.intermediate_size, config.vocab_size
    L, Q, KV = config.num_layers, config.q_dim, config.kv_dim

    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def normal(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    ks = jax.random.split(k_layers, 8)
    # Offset norms (Gemma) store w with effective scale (1 + w): identity is 0.
    norm_init = jnp.zeros if config.norm_offset else jnp.ones
    layers = {
        "attn_norm": norm_init((L, H), dtype),
        "wq": normal(ks[0], (L, H, Q), 1.0 / math.sqrt(H)),
        "wk": normal(ks[1], (L, H, KV), 1.0 / math.sqrt(H)),
        "wv": normal(ks[2], (L, H, KV), 1.0 / math.sqrt(H)),
        "wo": normal(ks[3], (L, Q, H), 1.0 / math.sqrt(Q)),
        "mlp_norm": norm_init((L, H), dtype),
    }
    if config.num_experts > 0:  # Mixtral family: per-expert MLP + router
        E = config.num_experts
        layers["w_router"] = normal(ks[7], (L, H, E), 1.0 / math.sqrt(H))
        layers["w_gate"] = normal(ks[4], (L, E, H, I), 1.0 / math.sqrt(H))
        layers["w_up"] = normal(ks[5], (L, E, H, I), 1.0 / math.sqrt(H))
        layers["w_down"] = normal(ks[6], (L, E, I, H), 1.0 / math.sqrt(I))
    else:
        layers["w_gate"] = normal(ks[4], (L, H, I), 1.0 / math.sqrt(H))
        layers["w_up"] = normal(ks[5], (L, H, I), 1.0 / math.sqrt(H))
        layers["w_down"] = normal(ks[6], (L, I, H), 1.0 / math.sqrt(I))
    if config.qkv_bias:  # Qwen2 family
        layers["bq"] = jnp.zeros((L, Q), dtype)
        layers["bk"] = jnp.zeros((L, KV), dtype)
        layers["bv"] = jnp.zeros((L, KV), dtype)
    if config.post_block_norms:  # Gemma-2: norms on attention/MLP outputs
        layers["post_attn_norm"] = norm_init((L, H), dtype)
        layers["post_mlp_norm"] = norm_init((L, H), dtype)
    params: Params = {
        "embed": normal(k_embed, (V, H), 1.0 / math.sqrt(H)),
        "layers": layers,
        "final_norm": norm_init((H,), dtype),
        "lm_head": normal(k_head, (H, V), 1.0 / math.sqrt(H)),
    }
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def rms_norm(x: jax.Array, weight: jax.Array, eps: float, offset: bool = False) -> jax.Array:
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    w = (1.0 + weight.astype(jnp.float32)).astype(x.dtype) if offset else weight
    return (x32 * scale).astype(x.dtype) * w


def _softcap(x: jax.Array, cap: float) -> jax.Array:
    """Gemma-2 soft capping: cap * tanh(x / cap)."""
    return cap * jnp.tanh(x / cap)


def _activation(config: ModelConfig, x: jax.Array) -> jax.Array:
    if config.act == "gelu":  # GeGLU (Gemma): tanh-approximate gelu
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _moe_mlp(config: ModelConfig, layer: Params, h: jax.Array) -> jax.Array:
    """Mixtral top-k token-choice MoE, computed densely over the stacked expert
    weights — one einsum per projection, no ragged gather/scatter, so XLA tiles
    it straight onto the MXU and GSPMD turns the expert axis sharding into
    expert parallelism. Router softmax is over the selected top-k only
    (Mixtral semantics), scattered back to a [B,S,E] combine weight."""
    E, K = config.num_experts, config.num_experts_per_tok
    router_logits = (h @ layer["w_router"]).astype(jnp.float32)  # [B,S,E]
    top_vals, top_idx = lax.top_k(router_logits, K)
    top_w = jax.nn.softmax(top_vals, axis=-1)  # [B,S,K]
    combine = (jax.nn.one_hot(top_idx, E, dtype=jnp.float32) * top_w[..., None]).sum(
        axis=-2
    )  # [B,S,E]

    gate = _activation(config, qeinsum("bsh,ehi->bsei", h, layer["w_gate"]))
    up = qeinsum("bsh,ehi->bsei", h, layer["w_up"])
    expert_out = qeinsum("bsei,eih->bseh", gate * up, layer["w_down"])
    return jnp.einsum("bseh,bse->bsh", expert_out, combine.astype(expert_out.dtype))


def _rope_inv_freq(d: int, theta: float, scaling) -> jax.Array:
    """Per-pair inverse frequencies, with optional llama3-style scaling
    (HF rope_type="llama3"; Llama-3.1/3.2 checkpoints): wavelengths past
    original_ctx/low_freq divide by ``factor``, short ones stay, the band
    between interpolates smoothly."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    if scaling is None:
        return inv_freq
    factor, low_freq_factor, high_freq_factor, orig_ctx = scaling
    wavelen = 2.0 * math.pi / inv_freq
    low_wavelen = orig_ctx / low_freq_factor
    high_wavelen = orig_ctx / high_freq_factor
    smooth = (orig_ctx / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor
    )
    interpolated = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    scaled = jnp.where(wavelen > low_wavelen, inv_freq / factor, interpolated)
    return jnp.where(wavelen < high_wavelen, inv_freq, scaled)


def rope_embed(
    x: jax.Array, positions: jax.Array, theta: float, scaling=None
) -> jax.Array:
    """Rotary embedding. x: [B, S, heads, D], positions: [B, S]."""
    d = x.shape[-1]
    inv_freq = _rope_inv_freq(d, theta, scaling)
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, S, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _gqa_scores(q: jax.Array, k: jax.Array) -> jax.Array:
    """q: [B, Sq, QH, D], k: [B, Sk, KVH, D] -> scores [B, QH, Sq, Sk]."""
    B, Sq, QH, D = q.shape
    KVH = k.shape[2]
    G = QH // KVH
    qg = q.reshape(B, Sq, KVH, G, D)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32)
    return scores.reshape(B, QH, Sq, k.shape[1])


def _gqa_scores_shared(q: jax.Array, k: jax.Array) -> jax.Array:
    """Shared-prefix scores: q [B, Sq, QH, D] vs R shared key sets
    k [R, Sk, KVH, D], batch rows grouped request-major (row b belongs to
    request b // (B//R)). Each prefix is stored ONCE and shared by its
    request's samples via a reshaped einsum — no materialized per-sample
    copies (the HBM saving behind n=32 on one chip), and no gather when
    several requests decode coalesced in one batch. R=1 is the single-request
    case (one prompt broadcast over all n samples)."""
    B, Sq, QH, D = q.shape
    R, Sk, KVH, _ = k.shape
    G = QH // KVH
    qg = q.reshape(R, B // R, Sq, KVH, G, D)
    scores = jnp.einsum("rnqhgd,rkhd->rnhgqk", qg, k, preferred_element_type=jnp.float32)
    return scores.reshape(B, QH, Sq, Sk)


def _gqa_values(weights: jax.Array, v: jax.Array) -> jax.Array:
    """weights: [B, QH, Sq, Sk], v: [B, Sk, KVH, D] -> [B, Sq, QH, D] f32.

    V stays in its cache dtype (bf16) with f32 MXU accumulation — an explicit
    astype(f32) here would materialize a double-width copy of the whole cache
    every decode step (HBM traffic is the decode bottleneck)."""
    B, QH, Sq, Sk = weights.shape
    KVH = v.shape[2]
    G = QH // KVH
    wg = weights.astype(v.dtype).reshape(B, KVH, G, Sq, Sk)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", wg, v, preferred_element_type=jnp.float32)
    return out.reshape(B, Sq, QH, v.shape[3])


def _gqa_values_shared(weights: jax.Array, v: jax.Array) -> jax.Array:
    """weights: [B, QH, Sq, Sk], R shared value sets v: [R, Sk, KVH, D] ->
    [B, Sq, QH, D] f32. Row grouping mirrors :func:`_gqa_scores_shared`."""
    B, QH, Sq, Sk = weights.shape
    R, _, KVH, _ = v.shape
    G = QH // KVH
    wg = weights.astype(v.dtype).reshape(R, B // R, KVH, G, Sq, Sk)
    out = jnp.einsum("rnhgqk,rkhd->rnqhgd", wg, v, preferred_element_type=jnp.float32)
    return out.reshape(B, Sq, QH, v.shape[3])


def _attn_qkv(
    config: ModelConfig, layer: Params, x: jax.Array, positions: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Shared attention head: pre-norm -> QKV projection (+ optional biases)
    -> head split -> RoPE. Factored out of :func:`_block` so the paged twin
    (:func:`_block_paged`) runs the exact same ops — bit-identity between the
    dense and paged decode paths holds by construction, not by replication."""
    B, Sq, _ = x.shape
    h = rms_norm(x, layer["attn_norm"], config.rms_eps, config.norm_offset)
    q, k, v = qdot(h, layer["wq"]), qdot(h, layer["wk"]), qdot(h, layer["wv"])
    if "bq" in layer:  # Qwen2-family QKV biases (static per-config structure)
        q, k, v = q + layer["bq"], k + layer["bk"], v + layer["bv"]
    q = q.reshape(B, Sq, config.num_heads, config.head_dim)
    k = k.reshape(B, Sq, config.num_kv_heads, config.head_dim)
    v = v.reshape(B, Sq, config.num_kv_heads, config.head_dim)

    q = rope_embed(q, positions, config.rope_theta, config.rope_scaling)
    k = rope_embed(k, positions, config.rope_theta, config.rope_scaling)
    return q, k, v


def _mlp_sublayer(config: ModelConfig, layer: Params, x: jax.Array) -> jax.Array:
    """Post-attention MLP sublayer with its residual (dense MLP or MoE)."""
    offset = config.norm_offset
    h = rms_norm(x, layer["mlp_norm"], config.rms_eps, offset)
    if "w_router" in layer:  # MoE (Mixtral)
        out = _moe_mlp(config, layer, h)
    else:
        gate = _activation(config, qdot(h, layer["w_gate"]))
        up = qdot(h, layer["w_up"])
        out = qdot(gate * up, layer["w_down"])
    if "post_mlp_norm" in layer:
        out = rms_norm(out, layer["post_mlp_norm"], config.rms_eps, offset)
    return x + out


def _attn_residual(
    config: ModelConfig, layer: Params, x: jax.Array, attn: jax.Array
) -> jax.Array:
    """Attention output projection plus the block's first residual."""
    out = qdot(attn, layer["wo"])
    if "post_attn_norm" in layer:
        out = rms_norm(out, layer["post_attn_norm"], config.rms_eps, config.norm_offset)
    return x + out


def _merge_prefix_tail(q, cache_k, cache_v, key_mask, scale, out_p, m_p, l_p):
    """Exact logsumexp merge of a prefix-phase partial (normalized out,
    running max m, denominator l — each [B, QH, Sq]-leading; single-query
    callers pass Sq=1) with the per-row generated-KV tail computed in XLA.
    Returns the merged attention [B, Sq, QH, D] f32 (caller casts/reshapes)."""
    s_g = _gqa_scores(q, cache_k) * scale  # [B, QH, Sq, G]
    s_g = jnp.where(key_mask[:, None, :, :], s_g, jnp.finfo(jnp.float32).min)
    m_g = jnp.max(s_g, axis=-1)  # [B, QH, Sq]
    p_g = jnp.exp(s_g - m_g[..., None])
    l_g = jnp.sum(p_g, axis=-1)  # [B, QH, Sq]
    out_g = _gqa_values(p_g, cache_v).transpose(0, 2, 1, 3)  # [B, QH, Sq, D]

    m = jnp.maximum(m_p, m_g)
    a_p = jnp.exp(m_p - m)
    a_g = jnp.exp(m_g - m)
    denom = l_p * a_p + l_g * a_g
    merged = (
        out_p * (l_p * a_p)[..., None] + out_g * a_g[..., None]
    ) / jnp.where(denom == 0.0, 1.0, denom)[..., None]
    return merged.transpose(0, 2, 1, 3)  # [B, Sq, QH, D]


def _block(
    config: ModelConfig,
    layer: Params,
    x: jax.Array,
    positions: jax.Array,
    kv: Tuple[jax.Array, jax.Array],
    write_index: Optional[jax.Array],
    key_mask: jax.Array,
    prefix_kv: Optional[Tuple[jax.Array, jax.Array]] = None,
    prefix_mask: Optional[jax.Array] = None,
    key_lengths: Optional[jax.Array] = None,
    prefix_lengths: Optional[jax.Array] = None,
    window_value=None,
    sp_ring_mesh=None,
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """One transformer block over (possibly cached) keys.

    x: [B, Sq, H]; kv: layer cache (k, v) each [B, Smax, KVH, D];
    write_index: scalar slot where this call's k/v are written (None = positions
    0..Sq, i.e. prefill); key_mask: [B|1, Sq, Smax] additive-mask booleans for the
    self cache; prefix_kv/prefix_mask: optional shared-prompt cache [R, P, KVH, D]
    and [1|B, Sq, P]; prefix_lengths: [R] valid prefix key counts (decode only —
    enables the Pallas shared-prefix decode kernel). ``sp_ring_mesh``: a Mesh
    marking the prefix KV as SEQUENCE-SHARDED over the mesh's data axis —
    decode attends it in place via ring attention (O(S/P) per device) instead
    of the replicated-prefix paths.
    """
    B, Sq, H = x.shape
    scale = config.query_scale or 1.0 / math.sqrt(config.head_dim)

    q, k, v = _attn_qkv(config, layer, x, positions)

    cache_k, cache_v = kv
    if write_index is None:
        cache_k = lax.dynamic_update_slice_in_dim(cache_k, k.astype(cache_k.dtype), 0, axis=1)
        cache_v = lax.dynamic_update_slice_in_dim(cache_v, v.astype(cache_v.dtype), 0, axis=1)
    elif getattr(write_index, "ndim", 0) == 1:
        # Per-ROW write offsets (speculative verify: rows have different
        # generated lengths) — a vmapped dynamic_update_slice per batch row.
        row_update = jax.vmap(
            lambda c, kk, off: lax.dynamic_update_slice_in_dim(c, kk, off, axis=0)
        )
        cache_k = row_update(cache_k, k.astype(cache_k.dtype), write_index)
        cache_v = row_update(cache_v, v.astype(cache_v.dtype), write_index)
    else:
        cache_k = lax.dynamic_update_slice_in_dim(
            cache_k, k.astype(cache_k.dtype), write_index, axis=1
        )
        cache_v = lax.dynamic_update_slice_in_dim(
            cache_v, v.astype(cache_v.dtype), write_index, axis=1
        )

    def mlp(y: jax.Array) -> jax.Array:
        return _mlp_sublayer(config, layer, y)

    def attn_out(attn: jax.Array) -> jax.Array:
        return _attn_residual(config, layer, x, attn)

    # Full-sequence prefill takes the Pallas flash path: prefix-length masking,
    # causal structure, attention softcap (Gemma-2) and sliding windows
    # (Mistral "all", Gemma-2 "alternating" via a dynamic per-layer window
    # scalar) are all kernel-supported.
    if (
        config.attention_impl == "flash"
        and write_index is None
        and prefix_kv is None
        and key_lengths is not None
    ):
        from ..ops.attention import flash_attention

        attn = flash_attention(
            q.transpose(0, 2, 1, 3),
            k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3),
            causal=True,
            key_lengths=key_lengths,
            sm_scale=scale,
            softcap=config.attn_softcap,
            window=window_value,
            interpret=jax.default_backend() != "tpu",
        ).transpose(0, 2, 1, 3)
        attn = attn.astype(x.dtype).reshape(B, Sq, config.q_dim)
        return mlp(attn_out(attn)), (cache_k, cache_v)

    # Continuation prefill (prefix-cache partial hit): suffix queries at
    # absolute positions write_index.. attend the full cache through the same
    # flash kernel in q_offset mode — no [Sq, Smax] score tensor in HBM, so
    # no 1 GB masked-XLA cap and no full-prefill fallback at long suffixes.
    # Keys beyond the written range are zeros from the padded cache seed and
    # sit above every valid query's causal horizon.
    if (
        config.attention_impl == "flash"
        and write_index is not None
        and getattr(write_index, "ndim", 0) == 0
        and Sq > 1
        and prefix_kv is None
    ):
        from ..ops.attention import flash_attention

        attn = flash_attention(
            q.transpose(0, 2, 1, 3),
            cache_k.transpose(0, 2, 1, 3),
            cache_v.transpose(0, 2, 1, 3),
            causal=True,
            sm_scale=scale,
            softcap=config.attn_softcap,
            window=window_value,
            q_offset=write_index,
            interpret=jax.default_backend() != "tpu",
        ).transpose(0, 2, 1, 3)
        attn = attn.astype(x.dtype).reshape(B, Sq, config.q_dim)
        return mlp(attn_out(attn)), (cache_k, cache_v)

    def _merge_tail(out_p, m_p, l_p):
        attn = _merge_prefix_tail(
            q, cache_k, cache_v, key_mask, scale, out_p, m_p, l_p
        )
        return attn.astype(x.dtype).reshape(B, Sq, config.q_dim)

    # Decode/verify step against a SEQUENCE-SHARDED prefix (ring attention):
    # the SP prefill left its KV sharded over the mesh's data axis; chunks
    # rotate the ring with online-softmax accumulation, so the prefix is never
    # gathered and long-context serving stays O(S/P) end-to-end. Sq == 1 is
    # the plain decode step; Sq > 1 is a speculative VERIFY block scoring the
    # whole draft window in one ring pass (all verify queries sit past the
    # prompt, so prefix visibility is non-causal and the same valid-column
    # masking applies).
    if (
        sp_ring_mesh is not None
        and write_index is not None
        and prefix_kv is not None
        and prefix_lengths is not None
        and config.attn_softcap is None
        and config.sliding_window is None
    ):
        from ..ops.ring_attention import ring_decode_prefix, ring_verify_prefix

        plen = prefix_lengths.reshape(-1)[0]  # ring path is single-request (R=1)
        if Sq == 1:
            out_p, m_p, l_p = ring_decode_prefix(
                sp_ring_mesh, q[:, 0], prefix_kv[0], prefix_kv[1], plen,
                sm_scale=scale,
            )
            out_p = out_p[:, :, None]  # [B, QH, 1, D]
            m_p = m_p[:, :, None]
            l_p = l_p[:, :, None]
        else:
            out_p, m_p, l_p = ring_verify_prefix(
                sp_ring_mesh,
                q.transpose(0, 2, 1, 3),  # [B, QH, Sq, D]
                prefix_kv[0],
                prefix_kv[1],
                plen,
                sm_scale=scale,
            )
        return mlp(attn_out(_merge_tail(out_p, m_p, l_p))), (cache_k, cache_v)

    # Decode step against a shared prefix: the Pallas decode kernel streams
    # each prefix KV block from HBM once per (request, kv head) and hits it
    # with the request's whole query tile; the short generated tail plus an
    # exact logsumexp merge stay in XLA. Gated to tile-friendly shapes
    # (query rows per request >= one sublane tile).
    if (
        config.decode_attention_impl == "flash"
        and config.sliding_window is None
        and config.attn_softcap is None
        and write_index is not None
        and Sq == 1
        and prefix_kv is not None
        and prefix_lengths is not None
        and (B // prefix_kv[0].shape[0]) * (config.num_heads // config.num_kv_heads) >= 8
    ):
        from ..ops.attention import decode_prefix_attention

        pk, pv = prefix_kv
        out_p, m_p, l_p = decode_prefix_attention(
            q[:, 0],
            pk,
            pv,
            prefix_lengths,
            sm_scale=scale,
            interpret=jax.default_backend() != "tpu",
        )
        return (
            mlp(attn_out(_merge_tail(out_p[:, :, None], m_p[:, :, None], l_p[:, :, None]))),
            (cache_k, cache_v),
        )

    scores = _gqa_scores(q, cache_k) * scale  # [B, QH, Sq, Smax] f32
    if config.attn_softcap is not None:
        scores = _softcap(scores, config.attn_softcap)
    neg = jnp.finfo(jnp.float32).min
    scores = jnp.where(key_mask[:, None, :, :], scores, neg)

    if prefix_kv is not None:
        pk, pv = prefix_kv
        p_scores = _gqa_scores_shared(q, pk) * scale  # [B, QH, Sq, P]
        if config.attn_softcap is not None:
            p_scores = _softcap(p_scores, config.attn_softcap)
        p_scores = jnp.where(prefix_mask[:, None, :, :], p_scores, neg)
        all_scores = jnp.concatenate([p_scores, scores], axis=-1)
        weights = jax.nn.softmax(all_scores, axis=-1)
        P = pk.shape[1]
        attn = _gqa_values_shared(weights[..., :P], pv) + _gqa_values(weights[..., P:], cache_v)
    else:
        weights = jax.nn.softmax(scores, axis=-1)
        attn = _gqa_values(weights, cache_v)

    attn = attn.astype(x.dtype).reshape(B, Sq, config.q_dim)
    return mlp(attn_out(attn)), (cache_k, cache_v)


def _local_layer_flags(config: ModelConfig) -> Optional[jax.Array]:
    """[L] bool: layer uses the windowed mask. None when no per-layer mixing
    (full causal everywhere, or every layer windowed)."""
    if config.sliding_window is None or config.sliding_window_layers == "all":
        return None
    # "alternating" (Gemma-2): even layers local, odd layers global.
    return jnp.arange(config.num_layers) % 2 == 0


def _apply_stack(
    config: ModelConfig,
    params: Params,
    x: jax.Array,
    positions: jax.Array,
    cache: KVCache,
    write_index: Optional[jax.Array],
    key_mask: jax.Array,
    prefix: Optional[KVCache] = None,
    prefix_mask: Optional[jax.Array] = None,
    key_lengths: Optional[jax.Array] = None,
    key_mask_global: Optional[jax.Array] = None,
    prefix_mask_global: Optional[jax.Array] = None,
    prefix_lengths: Optional[jax.Array] = None,
    sp_ring_mesh=None,
) -> Tuple[jax.Array, KVCache]:
    """Scan the layer stack. cache k/v: [L, B, Smax, KVH, D].

    When layers alternate local/global attention (Gemma-2), ``key_mask`` /
    ``prefix_mask`` hold the WINDOWED masks, the ``*_global`` twins hold the
    full-causal ones, and a scanned per-layer flag picks between them.
    """
    local_flags = _local_layer_flags(config) if key_mask_global is not None else None

    def body(carry, scanned):
        x = carry
        flag = scanned.get("flag")
        if flag is None:
            km, pm = key_mask, prefix_mask
            # Static per-model window ("all" layers or none).
            window_value = config.sliding_window
        else:
            km = jnp.where(flag, key_mask, key_mask_global)
            pm = (
                jnp.where(flag, prefix_mask, prefix_mask_global)
                if prefix_mask is not None
                else None
            )
            # Alternating layers: the scanned flag picks this layer's window
            # (a traced scalar — the flash kernel takes it dynamically).
            from ..ops.attention import NO_WINDOW

            window_value = jnp.where(
                flag, jnp.int32(config.sliding_window), jnp.int32(NO_WINDOW)
            )
        x, new_kv = _block(
            config,
            scanned["layers"],
            x,
            positions,
            scanned["kv"],
            write_index,
            km,
            prefix_kv=scanned.get("prefix"),
            prefix_mask=pm,
            key_lengths=key_lengths,
            prefix_lengths=prefix_lengths,
            window_value=window_value,
            sp_ring_mesh=sp_ring_mesh,
        )
        return x, new_kv

    # Optional scanned slots (shared prefix, per-layer window flags) are
    # present-or-absent dict keys — one scan covers every combination with a
    # statically known pytree structure.
    xs = {"layers": params["layers"], "kv": (cache.k, cache.v)}
    if prefix is not None:
        xs["prefix"] = (prefix.k, prefix.v)
    if local_flags is not None:
        xs["flag"] = local_flags
    x, new_kv = lax.scan(body, x, xs)

    return x, KVCache(k=new_kv[0], v=new_kv[1])


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def _embed(config: ModelConfig, params: Params, tokens: jax.Array) -> jax.Array:
    x = jnp.take(params["embed"], tokens, axis=0)
    if config.embed_scale:  # Gemma: normalize embedding magnitude
        x = x * jnp.asarray(math.sqrt(config.hidden_size), x.dtype)
    return x


def _logits(config: ModelConfig, params: Params, h: jax.Array) -> jax.Array:
    logits = qdot(h, params["lm_head"]).astype(jnp.float32)
    if config.logit_softcap is not None:
        logits = _softcap(logits, config.logit_softcap)
    return logits


def encode(
    config: ModelConfig,
    params: Params,
    tokens: jax.Array,
    pad_mask: jax.Array,
) -> jax.Array:
    """Final hidden states [B,S,H] — the on-device embedding provider only
    mean-pools hidden states. Under ``jax.jit`` the unused logits output (the
    lm_head projection, the single largest matmul in the network) is pruned by
    XLA dead-code elimination, so this thin wrapper costs nothing."""
    return forward(config, params, tokens, pad_mask)[1]


def forward(
    config: ModelConfig,
    params: Params,
    tokens: jax.Array,
    pad_mask: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Full-sequence causal forward (no cache). Returns (logits f32 [B,S,V],
    final hidden states [B,S,H])."""
    B, S = tokens.shape
    positions = jnp.cumsum(pad_mask.astype(jnp.int32), axis=1) - 1
    positions = jnp.maximum(positions, 0)
    x = _embed(config, params, tokens)

    causal = jnp.tril(jnp.ones((S, S), bool))
    key_mask_global = None
    if config.sliding_window is not None:  # query i sees keys (i-W, i]
        band = causal & jnp.triu(jnp.ones((S, S), bool), -(config.sliding_window - 1))
        if config.sliding_window_layers == "alternating":
            key_mask_global = causal[None, :, :] & pad_mask[:, None, :].astype(bool)
        causal = band
    key_mask = causal[None, :, :] & pad_mask[:, None, :].astype(bool)

    cache = init_cache(config, B, S)
    key_lengths = pad_mask.astype(jnp.int32).sum(axis=1)
    x, _ = _apply_stack(
        config,
        params,
        x,
        positions,
        cache,
        None,
        key_mask,
        key_lengths=key_lengths,
        key_mask_global=key_mask_global,
    )
    h = rms_norm(x, params["final_norm"], config.rms_eps, config.norm_offset)
    logits = _logits(config, params, h)
    return logits, h


def prefill(
    config: ModelConfig,
    params: Params,
    tokens: jax.Array,
    prompt_len: jax.Array,
) -> Tuple[jax.Array, KVCache]:
    """Prefill the shared prompt at batch=1. tokens: [1, S] (bucket-padded on the
    right), prompt_len: scalar valid length. Returns (last-token logits [1, V],
    prefix KVCache [L, 1, S, KVH, D])."""
    B, S = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    x = _embed(config, params, tokens)

    causal = jnp.tril(jnp.ones((S, S), bool))
    valid = jnp.arange(S)[None, :] < prompt_len  # [1, S]
    key_mask_global = None
    if config.sliding_window is not None:
        band = causal & jnp.triu(jnp.ones((S, S), bool), -(config.sliding_window - 1))
        if config.sliding_window_layers == "alternating":
            key_mask_global = causal[None, :, :] & valid[:, None, :]
        causal = band
    key_mask = causal[None, :, :] & valid[:, None, :]

    cache = init_cache(config, B, S)
    key_lengths = jnp.broadcast_to(prompt_len, (B,)).astype(jnp.int32)
    x, cache = _apply_stack(
        config,
        params,
        x,
        positions,
        cache,
        None,
        key_mask,
        key_lengths=key_lengths,
        key_mask_global=key_mask_global,
    )
    h = rms_norm(x, params["final_norm"], config.rms_eps, config.norm_offset)
    last = jnp.take_along_axis(h, (prompt_len - 1).reshape(B, 1, 1).astype(jnp.int32), axis=1)
    logits = _logits(config, params, last[:, 0, :])
    return logits, cache


def prefill_continue(
    config: ModelConfig,
    params: Params,
    suffix_tokens: jax.Array,
    cache: KVCache,
    prefix_len: jax.Array,
    total_len: jax.Array,
) -> Tuple[jax.Array, KVCache]:
    """Prefill a prompt SUFFIX against an already-computed prompt-prefix KV —
    the prefix-caching path (the reference has no model layer; its provider
    re-reads the full prompt every request).

    ``cache`` [L, 1, Btot, KVH, D] holds the reused prefix KV at positions
    0..prefix_len (rest arbitrary); suffix_tokens: [1, Sq] right-padded; the
    suffix KV is written in place at positions prefix_len.. and the UPDATED
    cache is returned — directly the decode loop's shared-prefix cache and
    the next cache entry. Attention masks are built over absolute positions,
    so sliding windows (static or alternating) and softcaps work unchanged.
    Returns (last-valid-token logits [1, V], updated KVCache).
    """
    B, Sq = suffix_tokens.shape
    Btot = cache.k.shape[2]
    positions = prefix_len + jnp.broadcast_to(jnp.arange(Sq)[None, :], (B, Sq))
    x = _embed(config, params, suffix_tokens)

    rows = prefix_len + jnp.arange(Sq)[None, :, None]  # absolute query positions
    cols = jnp.arange(Btot)[None, None, :]
    causal_abs = cols <= rows  # [1, Sq, Btot]
    key_mask_global = None
    if config.sliding_window is not None:
        band = causal_abs & (cols > rows - config.sliding_window)
        if config.sliding_window_layers == "alternating":
            key_mask_global = causal_abs
        causal_abs = band
    x, cache = _apply_stack(
        config,
        params,
        x,
        positions,
        cache,
        prefix_len,
        causal_abs,
        key_mask_global=key_mask_global,
    )
    h = rms_norm(x, params["final_norm"], config.rms_eps, config.norm_offset)
    last_row = (total_len - prefix_len - 1).reshape(B, 1, 1).astype(jnp.int32)
    last = jnp.take_along_axis(h, last_row, axis=1)
    logits = _logits(config, params, last[:, 0, :])
    return logits, cache


def prefill_chunk_step(
    config: ModelConfig,
    params: Params,
    chunk_tokens: jax.Array,
    cache: KVCache,
    cursor: jax.Array,
    valid_len: jax.Array,
) -> Tuple[jax.Array, KVCache]:
    """Extend a partially-filled prompt prefix by one chunk — the unit of
    chunked prefill (Sarathi-style: prompt ingestion interleaved with decode
    steps instead of one monolithic prefill).

    ``chunk_tokens``: [1, C] the next C prompt tokens, right-padded;
    ``cache``: [L, 1, B, KVH, D] staging cache holding positions 0..cursor;
    ``cursor``: scalar absolute offset of this chunk's first token;
    ``valid_len``: scalar count of non-pad tokens in the chunk.

    Semantically a chunk IS a prompt-suffix continuation, so this delegates to
    :func:`prefill_continue` — same ``_apply_stack``/``_block`` branches, same
    absolute-position masks — which is what makes the final chunk's logits
    byte-identical to whole-prompt prefill (pinned by the chunked-on/off
    differential in tests/test_chunked_prefill.py). Returns (last-valid-token
    logits [1, V] — meaningful only on the final chunk — and the updated
    cache).
    """
    return prefill_continue(
        config, params, chunk_tokens, cache, cursor, cursor + valid_len
    )


def prefill_chunk_step_paged(
    config: ModelConfig,
    params: Params,
    chunk_tokens: jax.Array,
    cache: KVCache,
    cursor: jax.Array,
    valid_len: jax.Array,
) -> Tuple[jax.Array, KVCache, jax.Array, jax.Array]:
    """Paged twin of :func:`prefill_chunk_step`: identical compute against the
    dense staging cache (byte-identity comes for free from the shared path),
    plus the chunk's freshly written KV columns sliced out so the caller can
    ``scatter_tokens`` them into the row's reserved page run at its current
    offset. Returns (logits [1, V], updated cache, k_cols [L, C, KVH, D],
    v_cols [L, C, KVH, D])."""
    C = chunk_tokens.shape[1]
    logits, cache = prefill_chunk_step(
        config, params, chunk_tokens, cache, cursor, valid_len
    )
    k_cols = jax.lax.dynamic_slice_in_dim(cache.k[:, 0], cursor, C, axis=1)
    v_cols = jax.lax.dynamic_slice_in_dim(cache.v[:, 0], cursor, C, axis=1)
    return logits, cache, k_cols, v_cols


def decode_step(
    config: ModelConfig,
    params: Params,
    token: jax.Array,
    step: jax.Array,
    prompt_len: jax.Array,
    gen_cache: KVCache,
    prefix: KVCache,
    sp_ring_mesh=None,
) -> Tuple[jax.Array, KVCache]:
    """One decode step for all samples against their shared prefix(es).

    token: [B] current tokens; step: scalar decode index (0-based); prompt_len:
    scalar, or [R] vector of per-request prompt lengths when R coalesced
    requests decode together (rows grouped request-major, B % R == 0);
    gen_cache: [L, B, G, KVH, D]; prefix: [L, R, P, KVH, D].
    ``sp_ring_mesh``: prefix is sequence-sharded over the mesh's data axis;
    attend it via ring decode (see ``_block``). Returns (logits f32 [B, V],
    updated gen_cache).
    """
    B = token.shape[0]
    G = gen_cache.max_len
    P = prefix.max_len

    # Per-ROW prompt length: scalar (legacy single-request) broadcasts to all
    # rows; an [R] vector repeats over each request's contiguous row group.
    pl = jnp.asarray(prompt_len, jnp.int32).reshape(-1)
    pl_row = jnp.repeat(pl, B // pl.shape[0], total_repeat_length=B)  # [B]

    positions = (pl_row + step)[:, None]
    x = _embed(config, params, token[:, None])

    # Self (generated) keys: slots 0..step inclusive are valid after the write.
    self_mask = (jnp.arange(G)[None, None, :] <= step) & jnp.ones((B, 1, 1), bool)
    # Prefix keys: positions < the row's prompt_len are valid.
    prefix_mask = jnp.arange(P)[None, None, :] < pl_row[:, None, None]
    self_mask_global = prefix_mask_global = None
    if config.sliding_window is not None:
        # Query position is prompt_len + step; key position k is visible iff
        # q_pos - k_pos < W. Gen slot s sits at position prompt_len + s.
        W = config.sliding_window
        if config.sliding_window_layers == "alternating":
            self_mask_global, prefix_mask_global = self_mask, prefix_mask
        self_mask = self_mask & (jnp.arange(G)[None, None, :] > step - W)
        prefix_mask = prefix_mask & (
            jnp.arange(P)[None, None, :] > pl_row[:, None, None] + step - W
        )

    x, gen_cache = _apply_stack(
        config,
        params,
        x,
        positions,
        gen_cache,
        step,
        self_mask,
        prefix=prefix,
        prefix_mask=prefix_mask,
        key_mask_global=self_mask_global,
        prefix_mask_global=prefix_mask_global,
        prefix_lengths=pl,
        sp_ring_mesh=sp_ring_mesh,
    )
    h = rms_norm(x, params["final_norm"], config.rms_eps, config.norm_offset)
    logits = _logits(config, params, h[:, 0, :])
    return logits, gen_cache


def verify_step(
    config: ModelConfig,
    params: Params,
    tokens: jax.Array,
    lengths: jax.Array,
    prompt_len: jax.Array,
    gen_cache: KVCache,
    prefix: KVCache,
    sp_ring_mesh=None,
) -> Tuple[jax.Array, KVCache]:
    """Speculative-decoding verification: score k+1 tokens per row in ONE
    forward (the draft-tree trunk of prompt-lookup decoding).

    tokens: [B, Sq] — row b's last accepted token followed by its drafts;
    lengths: [B] per-row generated-token counts (the write offset into the
    row's gen cache slots); prompt_len: scalar or [R] as in decode_step.
    KVs for all Sq positions are written at per-row offsets; acceptance-
    rejected slots simply get overwritten by a later verify.
    ``sp_ring_mesh``: as in :func:`decode_step` — the prefix KV is
    sequence-sharded over the mesh's data axis and each block verifies the
    draft window against it via ring attention. Returns
    (logits f32 [B, Sq, V] — logits[b, j] conditions on tokens[b, :j+1] —
    and the updated gen_cache).
    """
    B, Sq = tokens.shape
    G = gen_cache.max_len
    P = prefix.max_len

    pl = jnp.asarray(prompt_len, jnp.int32).reshape(-1)
    pl_row = jnp.repeat(pl, B // pl.shape[0], total_repeat_length=B)  # [B]
    lengths = lengths.astype(jnp.int32)

    j = jnp.arange(Sq)[None, :]  # query index within the verify block
    positions = pl_row[:, None] + lengths[:, None] + j  # [B, Sq]
    x = _embed(config, params, tokens)

    # Gen slot s holds the row's s-th generated token: query j sees slots
    # <= lengths + j (its own freshly written slot included, like decode).
    s = jnp.arange(G)[None, None, :]
    self_mask = s <= (lengths[:, None] + j)[:, :, None]  # [B, Sq, G]
    c = jnp.arange(P)[None, None, :]
    prefix_mask = (c < pl_row[:, None, None]) & jnp.ones((B, Sq, 1), bool)
    self_mask_global = prefix_mask_global = None
    if config.sliding_window is not None:
        W = config.sliding_window
        if config.sliding_window_layers == "alternating":
            self_mask_global, prefix_mask_global = self_mask, prefix_mask
        qpos_gen = (lengths[:, None] + j)[:, :, None]  # query's gen position
        self_mask = self_mask & (s > qpos_gen - W)
        prefix_mask = prefix_mask & (c > positions[:, :, None] - W)

    x, gen_cache = _apply_stack(
        config,
        params,
        x,
        positions,
        gen_cache,
        lengths,
        self_mask,
        prefix=prefix,
        prefix_mask=prefix_mask,
        key_mask_global=self_mask_global,
        prefix_mask_global=prefix_mask_global,
        prefix_lengths=pl,
        sp_ring_mesh=sp_ring_mesh,
    )
    h = rms_norm(x, params["final_norm"], config.rms_eps, config.norm_offset)
    logits = _logits(config, params, h)
    return logits, gen_cache


# ---------------------------------------------------------------------------
# Paged KV path (block-table gather over a flat page pool)
# ---------------------------------------------------------------------------

def _block_paged(
    config: ModelConfig,
    layer: Params,
    x: jax.Array,
    positions: jax.Array,
    pool_kv_l: Tuple[jax.Array, jax.Array],
    prefix_idx: jax.Array,
    gen_idx: jax.Array,
    write_index: jax.Array,
    key_mask: jax.Array,
    prefix_mask: jax.Array,
    prefix_lengths: Optional[jax.Array] = None,
    page_tables=None,
    page_size: Optional[int] = None,
    attn_impl: str = "xla",
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """Paged twin of :func:`_block` for the ``Sq == 1`` decode/verify step.

    KV comes from ONE layer's flat page pool (``pool_kv_l``) through block
    tables; attention runs in ``ops/paged_attention.py`` — the fused Pallas
    kernel when ``attn_impl`` selects it (block-table gather folded into the
    K/V load, no materialized copy) or the byte-identical XLA reference
    otherwise. Returns ``(x, (k_col, v_col))`` where the cols ``[B, KVH, D]``
    are this step's freshly computed column in pool dtype — the caller
    scatters them into the pool (the old path extracted the same column from
    the written gather transient via ``take_along_axis``; taking it straight
    from the projection is bit-identical and skips the round-trip).
    """
    from ..ops.paged_attention import (
        paged_decode_attention_pallas,
        paged_decode_attention_xla,
    )

    B, Sq, H = x.shape
    scale = config.query_scale or 1.0 / math.sqrt(config.head_dim)
    q, k, v = _attn_qkv(config, layer, x, positions)
    pool_k_l, pool_v_l = pool_kv_l
    k_col = k[:, 0].astype(pool_k_l.dtype)
    v_col = v[:, 0].astype(pool_v_l.dtype)

    if (
        attn_impl in ("pallas", "pallas_interpret")
        and Sq == 1
        and page_tables is not None
        and prefix_lengths is not None
        and config.attn_softcap is None
        and config.sliding_window is None
    ):
        prefix_pages, gen_pages, gen_phase = page_tables
        plen = jnp.asarray(prefix_lengths, jnp.int32).reshape(-1)
        pl_row = jnp.repeat(plen, B // plen.shape[0], total_repeat_length=B)
        attn = paged_decode_attention_pallas(
            q[:, 0],
            pool_k_l,
            pool_v_l,
            prefix_pages,
            gen_pages,
            gen_phase,
            k_col,
            v_col,
            pl_row,
            write_index.astype(jnp.int32),
            page_size=page_size,
            sm_scale=scale,
            interpret=attn_impl == "pallas_interpret",
        )[:, None]  # [B, 1, QH, D]
    else:
        # Same gate as _block's decode_prefix_attention branch, so a config
        # running flash decode on dense caches keeps it on paged ones.
        flash_prefix = (
            config.decode_attention_impl == "flash"
            and config.sliding_window is None
            and config.attn_softcap is None
            and Sq == 1
            and prefix_lengths is not None
            and (B // prefix_idx.shape[0]) * (config.num_heads // config.num_kv_heads) >= 8
        )
        attn = paged_decode_attention_xla(
            q,
            pool_k_l,
            pool_v_l,
            prefix_idx,
            gen_idx,
            k,
            v,
            write_index,
            key_mask,
            prefix_mask,
            sm_scale=scale,
            softcap=config.attn_softcap,
            prefix_lengths=prefix_lengths,
            flash_prefix=flash_prefix,
            interpret=jax.default_backend() != "tpu",
        )
    attn = attn.astype(x.dtype).reshape(B, Sq, config.q_dim)
    x = _attn_residual(config, layer, x, attn)
    return _mlp_sublayer(config, layer, x), (k_col, v_col)


def _apply_stack_paged(
    config: ModelConfig,
    params: Params,
    x: jax.Array,
    positions: jax.Array,
    pool_kv: KVCache,
    prefix_idx: jax.Array,
    gen_idx: jax.Array,
    write_index: jax.Array,
    key_mask: jax.Array,
    prefix_mask: jax.Array,
    key_mask_global: Optional[jax.Array] = None,
    prefix_mask_global: Optional[jax.Array] = None,
    prefix_lengths: Optional[jax.Array] = None,
    attn_impl: str = "xla",
    page_size: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Paged twin of :func:`_apply_stack`: per-layer KV lives in a flat page
    pool addressed through block tables instead of dense caches.

    pool_kv k/v: ``[L, total_pages * page_size, KVH, D]``; prefix_idx /
    gen_idx: int32 ``[B|R, P]`` / ``[B, G]`` flat pool slots for each row's
    prompt and generated positions (an ``[R, P]`` prefix table is shared
    request-major like the dense shared-prefix cache; out-of-table positions
    map into the trash page and are masked). Each layer runs
    :func:`_block_paged`, which fuses the block-table gather into attention —
    on the Pallas path nothing dense is ever materialized; on the XLA
    reference the gather happens INSIDE the layer scan so the transient is
    one layer's worth, 1/L of a dense cache.

    Unmasked pool values are bit-identical to dense cache contents and masked
    slots contribute an exact 0.0 through the softmax (scores forced to
    ``finfo.min`` before the max; ``exp`` underflows to 0; ``0 * finite ==
    0``), so the whole stack is byte-identical to :func:`_apply_stack` on
    equal inputs. Returns ``(x, k_cols, v_cols)`` with the cols
    ``[L, B, KVH, D]`` — each row's freshly written KV column for the
    caller's pool scatter.
    """
    local_flags = _local_layer_flags(config) if key_mask_global is not None else None

    page_tables = None
    if attn_impl in ("pallas", "pallas_interpret"):
        from ..ops.paged_attention import paged_attention_page_tables

        # Layer-invariant: hoisted out of the scan so the slot->page
        # arithmetic runs once per step, not once per layer.
        page_tables = paged_attention_page_tables(prefix_idx, gen_idx, page_size)

    def body(carry, scanned):
        x = carry
        flag = scanned.get("flag")
        if flag is None:
            km, pm = key_mask, prefix_mask
        else:
            km = jnp.where(flag, key_mask, key_mask_global)
            pm = jnp.where(flag, prefix_mask, prefix_mask_global)
        x, cols = _block_paged(
            config,
            scanned["layers"],
            x,
            positions,
            scanned["pool"],
            prefix_idx,
            gen_idx,
            write_index,
            km,
            pm,
            prefix_lengths=prefix_lengths,
            page_tables=page_tables,
            page_size=page_size,
            attn_impl=attn_impl,
        )
        return x, cols

    xs = {"layers": params["layers"], "pool": (pool_kv.k, pool_kv.v)}
    if local_flags is not None:
        xs["flag"] = local_flags
    x, cols = lax.scan(body, x, xs)
    return x, cols[0], cols[1]


def paged_verify_step(
    config: ModelConfig,
    params: Params,
    tokens: jax.Array,
    lengths: jax.Array,
    prompt_len: jax.Array,
    pool_kv: KVCache,
    prefix_idx: jax.Array,
    gen_idx: jax.Array,
    attn_impl: str = "xla",
    page_size: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Paged twin of :func:`verify_step` at ``Sq == 1`` — the continuous
    decode loop's step when its slots hold block tables into a shared page
    pool instead of dense per-row caches.

    tokens: [B, 1] current tokens; lengths: [B] generated counts (also each
    row's write offset into its gen slots); prompt_len: scalar or [R];
    pool_kv: the flat page pool ``[L, flat, KVH, D]``; prefix_idx [B|R, P] /
    gen_idx [B, G]: flat pool slots per logical position. Masks are built
    EXACTLY as in :func:`verify_step` (same shapes, same predicates), so the
    two paths select identical attention branches and produce bit-identical
    logits — pinned by tests/test_paged_differential.py. ``attn_impl``
    selects the fused attention ("xla" reference, "pallas" kernel, or the
    tests-only "pallas_interpret"); ``page_size`` is required for the Pallas
    paths (slot->page table derivation). Returns (logits f32 [B, 1, V],
    k_cols, v_cols [L, B, KVH, D]).
    """
    B, Sq = tokens.shape
    G = gen_idx.shape[1]
    P = prefix_idx.shape[1]

    pl = jnp.asarray(prompt_len, jnp.int32).reshape(-1)
    pl_row = jnp.repeat(pl, B // pl.shape[0], total_repeat_length=B)  # [B]
    lengths = lengths.astype(jnp.int32)

    j = jnp.arange(Sq)[None, :]
    positions = pl_row[:, None] + lengths[:, None] + j  # [B, Sq]
    x = _embed(config, params, tokens)

    s = jnp.arange(G)[None, None, :]
    self_mask = s <= (lengths[:, None] + j)[:, :, None]  # [B, Sq, G]
    c = jnp.arange(P)[None, None, :]
    prefix_mask = (c < pl_row[:, None, None]) & jnp.ones((B, Sq, 1), bool)
    self_mask_global = prefix_mask_global = None
    if config.sliding_window is not None:
        W = config.sliding_window
        if config.sliding_window_layers == "alternating":
            self_mask_global, prefix_mask_global = self_mask, prefix_mask
        qpos_gen = (lengths[:, None] + j)[:, :, None]
        self_mask = self_mask & (s > qpos_gen - W)
        prefix_mask = prefix_mask & (c > positions[:, :, None] - W)

    x, k_cols, v_cols = _apply_stack_paged(
        config,
        params,
        x,
        positions,
        pool_kv,
        prefix_idx,
        gen_idx,
        lengths,
        self_mask,
        prefix_mask,
        key_mask_global=self_mask_global,
        prefix_mask_global=prefix_mask_global,
        prefix_lengths=pl,
        attn_impl=attn_impl,
        page_size=page_size,
    )
    h = rms_norm(x, params["final_norm"], config.rms_eps, config.norm_offset)
    logits = _logits(config, params, h)
    return logits, k_cols, v_cols
