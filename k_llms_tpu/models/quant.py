"""Int8 weight-only quantization for the matmul weights.

The reference has no model layer at all (its "engine" is the OpenAI HTTP API,
`/root/reference/k_llms/resources/completions/completions.py:73`); this is a
capability of the local TPU engine. Autoregressive decode is HBM-bandwidth
bound: every step streams the full weight set from HBM. Storing matmul weights
as int8 (symmetric, per-output-channel scales) halves that traffic, and lets
8B-class weights fit a single v5e chip (16 GB HBM) with room for KV caches.

Design: a :class:`QTensor` pytree (int8 payload + f32 scale) flows through the
same params tree, ``lax.scan``, and ``pjit`` shardings as the bf16 weights.
``qdot(x, w)`` dispatches on the weight type, so the model code in
``models/llama.py`` is quantization-agnostic: the int8→bf16 cast happens inside
the fused matmul (weights are read from HBM as int8; the per-channel scale is
applied to the matmul output, so no dequantized copy is ever materialized).
Embeddings and norms stay bf16 — lookups only stream the rows they touch.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Union

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.w4matmul import Q4Tensor, pack_int4, supports_int4, w4_matmul


class QTensor(NamedTuple):
    """Symmetric per-output-channel int8 weight: ``q`` has the weight's shape
    [..., in, out]; ``scale`` is f32 [..., 1, out]."""

    q: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype


WeightLike = Union[jax.Array, QTensor, Q4Tensor]

# Matmul weights to quantize (all contract over axis -2). Embeddings and norms
# stay in the model dtype.
_QUANT_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
# Megatron-style tensor-parallel layout of the quantized matmuls: column-
# parallel weights shard output columns over the model axis; row-parallel
# weights shard the contraction axis (their matmul psums partials).
_COL_PARALLEL_KEYS = frozenset({"wq", "wk", "wv", "w_gate", "w_up"})
_ROW_PARALLEL_KEYS = frozenset({"wo", "w_down"})


def _dense_quant_shapes(config) -> "Dict[str, tuple]":
    """(K, N) of each dense quantized matmul (MoE expert stacks are 4D with
    the layer axis and int4-ineligible, so they are not listed)."""
    H, I = config.hidden_size, config.intermediate_size
    Q, KV = config.q_dim, config.kv_dim
    return {
        "wq": (H, Q),
        "wk": (H, KV),
        "wv": (H, KV),
        "wo": (Q, H),
        "w_gate": (H, I),
        "w_up": (H, I),
        "w_down": (I, H),
    }


def int4_mesh_compatible(config, tp: int) -> bool:
    """True when every int4-eligible weight can shard over ``tp`` model-axis
    devices without splitting a quantization group (row-parallel needs
    K % (GROUP*tp) == 0) or fracturing columns (col-parallel needs
    N % tp == 0). MoE configs keep int4 off the experts already."""
    from ..ops.w4matmul import GROUP

    if tp <= 1:
        return True
    if config.num_experts > 0:
        return False  # expert einsums have no sharded-int4 path
    shapes = dict(_dense_quant_shapes(config))
    shapes["lm_head"] = (config.hidden_size, config.vocab_size)
    slow = []
    for key, (k, n) in shapes.items():
        ndim = 2 if key == "lm_head" else 3
        if not _int4_eligible_shape(ndim, k, n):
            continue  # stays int8, XLA partitions it natively
        if key in _ROW_PARALLEL_KEYS:
            if k % (GROUP * tp):
                return False
            local_k, local_n = k // tp, n
        else:
            if n % tp:
                return False
            local_k, local_n = k, n // tp
        # Correct but slow: a local shard whose blocking misses the Pallas
        # kernel's grid takes the XLA dequant fallback — int4's HBM-traffic
        # win evaporates for that weight. Surface it. (Divisibility by ANY
        # block choice == divisibility by the smallest, since the choices are
        # multiples of it — single source of truth in ops/w4matmul.py.)
        from ..ops.w4matmul import KERNEL_K_BLOCKS, KERNEL_N_BLOCKS

        if local_k % min(KERNEL_K_BLOCKS) or local_n % min(KERNEL_N_BLOCKS):
            slow.append((key, (local_k, local_n)))
    if slow:
        import logging

        logging.getLogger(__name__).warning(
            "int4 on model parallel=%d for %s: local shards %s miss the w4a16 "
            "kernel blocking and will use the XLA dequant fallback (correct, "
            "but without the 4-bit HBM-traffic win)",
            tp,
            config.name,
            slow,
        )
    return True


def _quant_leaf_nodes(params: "Dict[str, Any]"):
    """The quantizable matmul leaf-nodes of a params tree (single source for
    every stored-layout probe)."""
    for key in _QUANT_LAYER_KEYS:
        yield params["layers"].get(key)
    yield params.get("lm_head")


def tree_has_q4(params: "Dict[str, Any]") -> bool:
    """True when any quantized matmul leaf is stored int4 (pre-quantized
    checkpoints keep their layout through quantize_weight_bits)."""
    return any(isinstance(w, Q4Tensor) for w in _quant_leaf_nodes(params))


def stored_quant_layout(params: "Dict[str, Any]") -> "str | None":
    """The quantization a params tree actually stores — 'int4' if any leaf is
    Q4Tensor, 'int8' if any is QTensor, None for a plain bf16 tree. Lets a
    caller follow a pre-quantized checkpoint's layout whatever flag was
    passed."""
    nodes = list(_quant_leaf_nodes(params))
    if any(isinstance(w, Q4Tensor) for w in nodes):
        return "int4"
    if any(isinstance(w, QTensor) for w in nodes):
        return "int8"
    return None


def align_quantized_specs(
    params: "Dict[str, Any]", qspecs: "Dict[str, Any]", pspecs: "Dict[str, Any]"
) -> "Dict[str, Any]":
    """Reconcile a spec tree with the ACTUAL layout of a pre-quantized params
    tree: quantize_weight_bits keeps a checkpoint's stored QTensor/Q4Tensor
    layout regardless of the requested bits, so out_shardings built from the
    request alone would diverge in pytree structure and crash pjit."""

    def reconcile(w, spec_node, weight_spec):
        if isinstance(w, Q4Tensor) and not isinstance(spec_node, Q4Tensor):
            return Q4Tensor(q=weight_spec, scale=weight_spec)
        if isinstance(w, QTensor) and not isinstance(spec_node, QTensor):
            parts = list(weight_spec)
            if len(parts) >= 2:
                parts[-2] = None
            return QTensor(q=weight_spec, scale=P(*parts))
        return spec_node

    layers = dict(qspecs["layers"])
    for key in _QUANT_LAYER_KEYS:
        layers[key] = reconcile(
            params["layers"].get(key), layers[key], pspecs["layers"][key]
        )
    out = dict(qspecs)
    out["layers"] = layers
    out["lm_head"] = reconcile(params.get("lm_head"), qspecs["lm_head"], pspecs["lm_head"])
    return out


def mark_int4_partitioning(params: "Dict[str, Any]", mesh) -> "Dict[str, Any]":
    """Stamp every Q4Tensor leaf-node with its tensor-parallel layout + mesh so
    ``qdot`` routes through the shard_mapped kernel. Idempotent; trees without
    Q4 nodes pass through unchanged (checkpoint loads arrive unmarked)."""
    layers = dict(params["layers"])
    for key in _QUANT_LAYER_KEYS:
        w = layers.get(key)
        if isinstance(w, Q4Tensor):
            part = "col" if key in _COL_PARALLEL_KEYS else "row"
            layers[key] = Q4Tensor(w.q, w.scale, part=part, mesh=mesh)
    out = dict(params)
    out["layers"] = layers
    head = out.get("lm_head")
    if isinstance(head, Q4Tensor):
        out["lm_head"] = Q4Tensor(head.q, head.scale, part="col", mesh=mesh)
    return out


def quantize_weight(w: jax.Array) -> QTensor:
    """Symmetric int8 per-output-channel: scale over the contraction axis (-2)."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale)


def qdot(x: jax.Array, w: WeightLike) -> jax.Array:
    """``x @ w`` for a plain array, a QTensor, or a Q4Tensor. For QTensor the
    int8 payload is cast inside the matmul (HBM reads stay int8) and the
    per-channel scale is applied to the output. For Q4Tensor the Pallas w4a16
    kernel unpacks nibbles in VMEM (HBM reads stay int4); off-TPU the kernel
    runs in interpret mode only for realistic shapes — tiny test shapes take
    the XLA dequant reference inside :func:`w4_matmul`."""
    if isinstance(w, Q4Tensor):
        x2 = x.reshape(-1, x.shape[-1])
        interpret = jax.default_backend() != "tpu"
        if w.part is not None and w.mesh is not None:
            from ..ops.w4matmul import w4_matmul_tp

            out = w4_matmul_tp(x2, w, interpret=interpret)
        else:
            out = w4_matmul(x2, w, interpret=interpret)
        return out.reshape(*x.shape[:-1], w.q.shape[-1])
    if isinstance(w, QTensor):
        out = x @ w.q.astype(x.dtype)
        return out * w.scale[..., 0, :].astype(out.dtype)
    return x @ w


def qeinsum(spec: str, x: jax.Array, w: WeightLike) -> jax.Array:
    """``einsum(spec, x, w)`` for a plain array or QTensor weight. Requires the
    output's trailing axes to line up with the weight's non-contracted axes
    (true for the MoE expert einsums: "bsh,ehi->bsei", "bsei,eih->bseh"), so
    the squeezed per-channel scale broadcasts onto the output."""
    if isinstance(w, QTensor):
        out = jnp.einsum(spec, x, w.q.astype(x.dtype))
        return out * w.scale[..., 0, :].astype(out.dtype)
    return jnp.einsum(spec, x, w)


def _int4_eligible_shape(ndim: int, k: int, n: int) -> bool:
    """Q4 needs whole 256-row K blocks and 128-col N blocks; MoE expert stacks
    ([L, E, K, N], ndim 4) stay int8 — their einsum contraction has no w4
    kernel. Tiny test models fail the divisibility and stay int8 too. Single
    predicate for BOTH the quantize path and the random-init path, so the two
    always build the same QTensor/Q4Tensor tree layout for a given config."""
    return ndim <= 3 and supports_int4(k) and n % 128 == 0


def _int4_eligible(w: jax.Array) -> bool:
    return _int4_eligible_shape(w.ndim, w.shape[-2], w.shape[-1])


def quantize_weight_bits(w: WeightLike, bits: int) -> WeightLike:
    if isinstance(w, (QTensor, Q4Tensor)):
        # Already quantized — e.g. an orbax checkpoint of a quantized tree
        # loaded with the quantization flag still set. Keep the stored layout
        # (re-quantizing int8<->int4 from the lossy payload would only lose
        # more precision).
        return w
    if bits == 4 and _int4_eligible(w):
        return pack_int4(w)
    return quantize_weight(w)


def quantize_params(params: Dict[str, Any], bits: int = 8) -> Dict[str, Any]:
    """Quantize the seven block matmuls and lm_head; leave embed/norms as-is.

    ``bits=4`` packs eligible weights group-wise int4 (:mod:`ops.w4matmul`);
    ineligible ones (MoE expert stacks, non-divisible shapes) fall back int8.
    """
    layers = dict(params["layers"])
    for key in _QUANT_LAYER_KEYS:
        layers[key] = quantize_weight_bits(layers[key], bits)
    out = dict(params)
    out["layers"] = layers
    out["lm_head"] = quantize_weight_bits(params["lm_head"], bits)
    return out


def init_params_quantized(
    config, key: jax.Array, dtype=None, bits: int = 8, dist: str = "random"
) -> Dict[str, Any]:
    """Random int8-quantized init, building the QTensor tree DIRECTLY.

    For synthetic flagship benches: an 8B bf16 tree (~16 GB) cannot sit in one
    v5e chip's HBM next to its int8 copy during quantization, so the usual
    init-then-quantize path is unusable at that scale. Here the int8 payloads
    are drawn uniformly and scales are constants chosen so effective weights
    have ~N(0, 1/fan_in) magnitude (finite logits; a random model is all a
    synthetic bench needs). Mirrors the tree structure of
    ``llama.init_params`` + ``quantize_params``.

    ``dist="cheap"`` replaces every PRNG draw with a broadcast deterministic
    pattern (same shapes/scales, zero threefry work). For sharding dry runs on
    virtual CPU meshes: non-partitionable threefry gets REPLICATED under
    GSPMD — every virtual device computes the full billion-element draw — so
    a random 8B-width init costs minutes of host time that validates nothing
    the pattern init doesn't (the dry run checks layouts and compiled
    programs, not weight statistics).
    """
    import math

    if dist not in ("random", "cheap"):
        raise ValueError(f"Unknown dist {dist!r}; use 'random' or 'cheap'")
    cheap = dist == "cheap"
    dtype = dtype or config.jax_dtype
    H, I, V = config.hidden_size, config.intermediate_size, config.vocab_size
    L, Q, KV = config.num_layers, config.q_dim, config.kv_dim

    def _pattern_i8(shape) -> jax.Array:
        # Varies along the output-channel axis only: broadcast is trivially
        # partitionable, and matmul outputs stay non-degenerate.
        row = ((jnp.arange(shape[-1]) * 37) % 251 - 125).astype(jnp.int8)
        return jnp.broadcast_to(row, shape)

    def qinit(k, shape) -> WeightLike:
        K, N = shape[-2], shape[-1]
        if bits == 4 and _int4_eligible_shape(len(shape), K, N):
            from ..ops.w4matmul import GROUP

            # Random packed bytes = two uniform nibbles in [-8, 7] apiece
            # (std = sqrt(E[k^2]-mu^2) over -8..7 ~= 4.61); scale so effective
            # weights are ~N(0, 1/fan_in).
            nibble_std = math.sqrt(sum(v * v for v in range(-8, 8)) / 16 - 0.25)
            pshape = shape[:-2] + (K // 2, N)
            q = (
                _pattern_i8(pshape)
                if cheap
                else jax.random.randint(k, pshape, -128, 128, jnp.int8)
            )
            scale_val = 1.0 / (nibble_std * math.sqrt(K))
            scale = jnp.full(shape[:-2] + (K // GROUP, N), scale_val, jnp.float32)
            return Q4Tensor(q=q, scale=scale)
        q = (
            _pattern_i8(shape)
            if cheap
            else jax.random.randint(k, shape, -127, 128, jnp.int8)
        )
        # std(uniform int8) = 127/sqrt(3); scale it to 1/sqrt(fan_in).
        scale_val = math.sqrt(3.0) / (127.0 * math.sqrt(shape[-2]))
        scale = jnp.full(shape[:-2] + (1, shape[-1]), scale_val, jnp.float32)
        return QTensor(q=q, scale=scale)

    def normal(k, shape, scale):
        if cheap:
            row = ((jnp.arange(shape[-1]) * 53) % 17 - 8).astype(jnp.float32) / 8.0
            return jnp.broadcast_to(row * scale, shape).astype(dtype)
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    k_embed, k_layers, k_head = jax.random.split(key, 3)
    ks = jax.random.split(k_layers, 8)
    norm_init = jnp.zeros if config.norm_offset else jnp.ones
    layers: Dict[str, Any] = {
        "attn_norm": norm_init((L, H), dtype),
        "wq": qinit(ks[0], (L, H, Q)),
        "wk": qinit(ks[1], (L, H, KV)),
        "wv": qinit(ks[2], (L, H, KV)),
        "wo": qinit(ks[3], (L, Q, H)),
        "mlp_norm": norm_init((L, H), dtype),
    }
    if config.num_experts > 0:
        E = config.num_experts
        layers["w_router"] = normal(ks[7], (L, H, E), 1.0 / math.sqrt(H))
        layers["w_gate"] = qinit(ks[4], (L, E, H, I))
        layers["w_up"] = qinit(ks[5], (L, E, H, I))
        layers["w_down"] = qinit(ks[6], (L, E, I, H))
    else:
        layers["w_gate"] = qinit(ks[4], (L, H, I))
        layers["w_up"] = qinit(ks[5], (L, H, I))
        layers["w_down"] = qinit(ks[6], (L, I, H))
    if config.qkv_bias:
        layers["bq"] = jnp.zeros((L, Q), dtype)
        layers["bk"] = jnp.zeros((L, KV), dtype)
        layers["bv"] = jnp.zeros((L, KV), dtype)
    if config.post_block_norms:
        layers["post_attn_norm"] = norm_init((L, H), dtype)
        layers["post_mlp_norm"] = norm_init((L, H), dtype)
    return {
        "embed": normal(k_embed, (V, H), 1.0 / math.sqrt(H)),
        "layers": layers,
        "final_norm": norm_init((H,), dtype),
        "lm_head": qinit(k_head, (H, V)),
    }


def quantized_param_specs(
    specs: Dict[str, Any], bits: int = 8, config=None
) -> Dict[str, Any]:
    """Map a bf16 param-spec tree to the quantized tree: the int8 payload keeps
    the weight's spec; the scale keeps it too except on the contraction axis
    (size 1 after the keepdims reduce — an axis of size 1 can't shard).

    With ``bits=4`` (requires ``config`` for the shapes), int4-eligible keys
    get Q4Tensor spec nodes instead — both the packed payload ([.., K/2, N])
    and the per-group scale ([.., K/GROUP, N]) keep the weight's spec, since
    group packing is blocked along the contraction axis."""

    def scale_spec(spec: P) -> P:
        parts = list(spec)
        if len(parts) >= 2:
            parts[-2] = None
        return P(*parts)

    q4_keys = set()
    if bits == 4 and config is not None:
        for key, (k, n) in _dense_quant_shapes(config).items():
            if config.num_experts > 0 and key in ("w_gate", "w_up", "w_down"):
                continue  # 4D expert stacks stay int8
            if _int4_eligible_shape(3, k, n):
                q4_keys.add(key)
        if _int4_eligible_shape(2, config.hidden_size, config.vocab_size):
            q4_keys.add("lm_head")

    def qspec(key: str, spec: P):
        if key in q4_keys:
            return Q4Tensor(q=spec, scale=spec)
        return QTensor(q=spec, scale=scale_spec(spec))

    layers = dict(specs["layers"])
    for key in _QUANT_LAYER_KEYS:
        layers[key] = qspec(key, layers[key])
    out = dict(specs)
    out["layers"] = layers
    out["lm_head"] = qspec("lm_head", specs["lm_head"])
    return out
