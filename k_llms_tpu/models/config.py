"""Model architecture configs.

Flagship target is Llama-3-8B (BASELINE.md north star); the 1B config is the
single-v5e-chip bench model (8B bf16 weights alone exceed one chip's 16 GB HBM —
8B runs tensor-parallel over the mesh), and ``tiny`` keeps CI compiles fast.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict

import jax.numpy as jnp


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 500000.0
    # Llama-3.1/3.2-style frequency-dependent RoPE scaling:
    # (factor, low_freq_factor, high_freq_factor, original_max_position).
    # None = vanilla RoPE. Long wavelengths (past original_max/low_freq)
    # divide by factor, short ones keep, the band between interpolates —
    # matching HF's rope_type="llama3".
    rope_scaling: "tuple[float, float, float, int] | None" = None
    rms_eps: float = 1e-5
    max_seq_len: int = 8192
    dtype: str = "bfloat16"
    # Prefill attention implementation: "xla" (einsum, runs anywhere) or
    # "flash" (Pallas TPU kernel, ops/attention.py; ~1.3x prefill attention
    # speedup at 2k context on v5e).
    attention_impl: str = "xla"
    # Decode-step attention: "xla" (default) or "flash" (Pallas shared-prefix
    # kernel, ops/attention.py::decode_prefix_attention — streams each prefix
    # KV block once per request with the whole query tile on the MXU).
    # Measured on v5e at the 8B/int8/n=32/256-token-prefix flagship config the
    # kernel is 0.94x of XLA: decode there is WEIGHT-streaming-bound
    # (8.6 GB/step vs ~34 MB of prefix KV), so kernel call overhead outweighs
    # the attention win; it's an opt-in for long-prefix regimes.
    decode_attention_impl: str = "xla"
    # Architecture variants beyond Llama:
    # - qkv_bias: additive bias on q/k/v projections (Qwen2 family).
    # - sliding_window: each query attends only to the last W keys
    #   (Mistral family); None = full causal. Forces the XLA attention path.
    # - sliding_window_layers: "all" (every layer windowed — Mistral) or
    #   "alternating" (even layers windowed, odd layers global — Gemma-2).
    qkv_bias: bool = False
    sliding_window: "int | None" = None
    sliding_window_layers: str = "all"
    # Gemma-family variants:
    # - act: MLP gate activation, "silu" (Llama) or "gelu" (GeGLU).
    # - norm_offset: RMSNorm scales by (1 + w) instead of w.
    # - embed_scale: multiply token embeddings by sqrt(hidden_size).
    # - post_block_norms: Gemma-2 extra norms on the attention and MLP outputs
    #   (before each residual add).
    # - attn_softcap / logit_softcap: cap*tanh(x/cap) on attention scores /
    #   final logits. Softcaps force the XLA attention path.
    # - query_scale: attention score scale; None = 1/sqrt(head_dim).
    act: str = "silu"
    norm_offset: bool = False
    embed_scale: bool = False
    post_block_norms: bool = False
    attn_softcap: "float | None" = None
    logit_softcap: "float | None" = None
    query_scale: "float | None" = None
    # Mixture-of-experts (Mixtral family): every MLP becomes num_experts
    # experts with top-k token-choice routing. 0 = dense MLP.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # byte tokenizer vocab fits any vocab_size >= 260; HF tokenizers use the full space
    bos_token_id: int = 256
    eos_token_id: int = 257
    pad_token_id: int = 258

    @property
    def jax_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    def with_(self, **kw) -> "ModelConfig":
        return replace(self, **kw)


_REGISTRY: Dict[str, ModelConfig] = {}


def register_config(config: ModelConfig) -> ModelConfig:
    _REGISTRY[config.name] = config
    return config


def get_config(name: str) -> ModelConfig:
    key = name.lower()
    if key not in _REGISTRY:
        raise KeyError(f"Unknown model {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


register_config(
    ModelConfig(
        name="llama-3-8b",
        attention_impl="flash",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=500000.0,
        max_seq_len=8192,
    )
)

register_config(
    ModelConfig(
        name="llama-3.2-1b",
        attention_impl="flash",
        vocab_size=128256,
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=16,
        num_heads=32,
        num_kv_heads=8,
        head_dim=64,
        rope_theta=500000.0,
        # Llama-3.2 checkpoints ship rope_type="llama3" with factor 32.
        rope_scaling=(32.0, 1.0, 4.0, 8192),
        max_seq_len=8192,
    )
)

# Bench-scale model with a byte-level vocab: all FLOPs in the transformer stack,
# negligible embedding table, fits one v5e chip with room for n=32 KV caches.
register_config(
    ModelConfig(
        name="llama-1b-byte",
        attention_impl="flash",
        vocab_size=512,
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=16,
        num_heads=16,
        num_kv_heads=8,
        head_dim=128,
        max_seq_len=4096,
    )
)

# Gemma-2 family: GeGLU, (1+w) RMSNorm, post-block norms, sqrt(H) embedding
# scale, attention + final-logit softcaps, alternating local/global attention,
# tied embeddings, big head_dim with a fixed query scale.
register_config(
    ModelConfig(
        name="gemma-2-2b",
        vocab_size=256000,  # HF gemma-2 safetensors layout (not the 256128 padded Flax release)
        hidden_size=2304,
        intermediate_size=9216,
        num_layers=26,
        num_heads=8,
        num_kv_heads=4,
        head_dim=256,
        rope_theta=10000.0,
        rms_eps=1e-6,
        max_seq_len=8192,
        sliding_window=4096,
        sliding_window_layers="alternating",
        act="gelu",
        norm_offset=True,
        embed_scale=True,
        post_block_norms=True,
        attn_softcap=50.0,
        logit_softcap=30.0,
        query_scale=256.0**-0.5,  # query_pre_attn_scalar=256
        bos_token_id=2,
        eos_token_id=1,
        pad_token_id=0,
    )
)

register_config(
    ModelConfig(
        name="gemma-2-9b",
        vocab_size=256000,  # HF gemma-2 safetensors layout (not the 256128 padded Flax release)
        hidden_size=3584,
        intermediate_size=14336,
        num_layers=42,
        num_heads=16,
        num_kv_heads=8,
        head_dim=256,
        rope_theta=10000.0,
        rms_eps=1e-6,
        max_seq_len=8192,
        sliding_window=4096,
        sliding_window_layers="alternating",
        act="gelu",
        norm_offset=True,
        embed_scale=True,
        post_block_norms=True,
        attn_softcap=50.0,
        logit_softcap=30.0,
        query_scale=256.0**-0.5,
        bos_token_id=2,
        eos_token_id=1,
        pad_token_id=0,
    )
)

# Qwen2 family: Llama architecture + QKV biases, 1e6 rope theta.
register_config(
    ModelConfig(
        name="qwen2-7b",
        attention_impl="flash",
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        head_dim=128,
        rope_theta=1000000.0,
        rms_eps=1e-6,
        max_seq_len=8192,
        qkv_bias=True,
        bos_token_id=151643,
        eos_token_id=151645,
        pad_token_id=151643,
    )
)

register_config(
    ModelConfig(
        name="qwen2.5-0.5b",
        attention_impl="flash",
        vocab_size=151936,
        hidden_size=896,
        intermediate_size=4864,
        num_layers=24,
        num_heads=14,
        num_kv_heads=2,
        head_dim=64,
        rope_theta=1000000.0,
        rms_eps=1e-6,
        max_seq_len=8192,
        qkv_bias=True,
        bos_token_id=151643,
        eos_token_id=151645,
        pad_token_id=151643,
    )
)

# Mixtral family: Mistral attention + 8-expert top-2 MoE MLPs. Experts shard
# over the "model" mesh axis (expert parallelism).
register_config(
    ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=1000000.0,
        rms_eps=1e-5,
        max_seq_len=8192,
        num_experts=8,
        num_experts_per_tok=2,
        bos_token_id=1,
        eos_token_id=2,
        pad_token_id=2,
    )
)

# Mistral family: Llama architecture + sliding-window attention.
register_config(
    ModelConfig(
        name="mistral-7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=10000.0,
        rms_eps=1e-5,
        max_seq_len=8192,
        sliding_window=4096,
        bos_token_id=1,
        eos_token_id=2,
        pad_token_id=2,
    )
)

register_config(
    ModelConfig(
        name="tiny",
        vocab_size=512,
        hidden_size=64,
        intermediate_size=160,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        max_seq_len=4096,
        dtype="float32",
    )
)
