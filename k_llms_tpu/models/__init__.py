"""Local model zoo (pure-JAX, TPU-first).

The reference has no model layer — its "hardware" is the OpenAI HTTP API
(SURVEY.md §1). This package supplies the local replacement: functional
Llama-family transformers (GQA + RoPE + RMSNorm + SwiGLU) as parameter pytrees
plus jit-compiled apply functions, designed for pjit/GSPMD sharding over a
(data, model) mesh.
"""

from .config import ModelConfig, get_config, register_config
from .llama import init_params, forward, decode_step, prefill

__all__ = [
    "ModelConfig",
    "get_config",
    "register_config",
    "init_params",
    "forward",
    "prefill",
    "decode_step",
]
