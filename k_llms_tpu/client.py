"""Client facade: KLLMs / AsyncKLLMs.

Parity target: `/root/reference/k_llms/client.py` — ``KLLMs`` :31-44,
``AsyncKLLMs`` :47-60, ``Chat``/``AsyncChat`` :63-72, batched ``get_embeddings``
helper with token cropping :75-122. The OpenAI client inside becomes a pluggable
backend: ``KLLMs(backend="tpu", model="llama-3-8b")`` runs everything locally on
the device mesh; ``backend="fake"`` is the hermetic test double;
``backend="openai"`` reproduces the reference's HTTP flow.
"""

from __future__ import annotations

from typing import Any, List, Optional, Union

from .backends.base import Backend, resolve_backend
from .resources.completions import AsyncCompletions, Completions

# Embedding crop limit kept from the reference (`client.py:12`); the local
# embedding path enforces the same cap so degradation behavior matches.
MAX_EMBEDDING_TOKENS = 8191


class _BaseKLLMs:
    def __init__(
        self,
        backend: Union[str, Backend, None] = None,
        model: Optional[str] = None,
        **backend_kwargs: Any,
    ):
        self._backend = resolve_backend(backend, **backend_kwargs)
        self.default_model = model or "llama-3-8b"

    @property
    def backend(self) -> Backend:
        return self._backend

    @property
    def client(self) -> Backend:
        """The underlying engine (the reference exposes its OpenAI client here)."""
        return self._backend

    def get_embeddings(
        self,
        texts: List[str],
        model: str = "local",
        batch_size: int = 2048,
        verbose: bool = False,
    ) -> List[List[float]]:
        """Batched embeddings helper (reference `client.py:75-122`). Batch-size
        chunking kept; pricing accounting is moot for a local model."""
        embeddings: List[List[float]] = []
        for idx in range(0, len(texts), batch_size):
            embeddings.extend(self._backend.embeddings(texts[idx : idx + batch_size]))
        return embeddings


class KLLMs(_BaseKLLMs):
    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self.chat = Chat(self)


class AsyncKLLMs(_BaseKLLMs):
    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self.chat = AsyncChat(self)

    async def aget_embeddings(self, texts: List[str], **kwargs: Any) -> List[List[float]]:
        import asyncio

        return await asyncio.to_thread(lambda: self.get_embeddings(texts, **kwargs))


class Chat:
    def __init__(self, wrapper: KLLMs):
        self._wrapper = wrapper
        self.completions = Completions(wrapper)


class AsyncChat:
    def __init__(self, wrapper: AsyncKLLMs):
        self._wrapper = wrapper
        self.completions = AsyncCompletions(wrapper)
