"""Client facade: KLLMs / AsyncKLLMs.

Parity target: `/root/reference/k_llms/client.py` — ``KLLMs`` :31-44,
``AsyncKLLMs`` :47-60, ``Chat``/``AsyncChat`` :63-72, batched ``get_embeddings``
helper with token cropping :75-122. The OpenAI client inside becomes a pluggable
backend: ``KLLMs(backend="tpu", model="llama-3-8b")`` runs everything locally on
the device mesh; ``backend="fake"`` is the hermetic test double;
``backend="openai"`` reproduces the reference's HTTP flow; and
``KLLMs(backend="replicas", members=[...])`` serves from a
:class:`~k_llms_tpu.reliability.replicas.ReplicaSet` — N member engines with
health-aware routing, mid-flight failover, and hedged dispatch — behind the
same client surface.
"""

from __future__ import annotations

from typing import Any, List, Optional, Union

from .backends.base import Backend, resolve_backend
from .resources.completions import AsyncCompletions, Completions

# Embedding model caps and pricing, kept bit-identical to the reference
# (`client.py:12-13`). "local" is the on-device path: same 8191 crop cap so the
# degradation behavior matches, zero price.
MAX_TOKENS_PER_MODEL = {
    "local": 8191,
    "text-embedding-3-small": 8191,
    "text-embedding-3-large": 8191,
}
PRICING = {"local": 0.0, "text-embedding-3-small": 0.020, "text-embedding-3-large": 0.13}


def _progress_range(stop: int, step: int, verbose: bool):
    if verbose:
        try:
            import tqdm

            return tqdm.trange(0, stop, step)
        except ImportError:  # pragma: no cover
            pass
    return range(0, stop, step)


def _resolve_embedding_model(backend: Backend, model: str) -> str:
    """Map the sentinel "local" to whatever model the backend actually embeds
    with, so crop caps and pricing follow the model that gets hit. A model the
    USER names must be known (reference `client.py:95-96`); a backend default
    outside the table is allowed — it falls back to the default cap and $0."""
    if model != "local":
        if model not in MAX_TOKENS_PER_MODEL:
            raise ValueError(
                f"Model {model} not supported. Available models: "
                f"{list(MAX_TOKENS_PER_MODEL.keys())}"
            )
        return model
    effective = getattr(backend, "embedding_model_name", "local")
    if effective not in PRICING and getattr(backend, "bills_usage", False):
        # A PAID backend defaulting to a model we can't price must fail loudly
        # rather than silently billing $0; free/local custom embedders pass.
        raise ValueError(
            f"Model {effective} not supported. Available models: "
            f"{list(MAX_TOKENS_PER_MODEL.keys())}"
        )
    return effective


def _embed_batches(
    backend: Backend,
    processed: List[str],
    model: str,
    batch_size: int,
    verbose: bool,
    embeddings: List[List[float]],
    price_acc: List[float],
) -> None:
    """Shared batching/pricing loop (reference `client.py:108-117`): extends
    ``embeddings`` and ``price_acc[0]`` in place per batch, so a retry after a
    partial failure keeps billing what the failed attempt already spent."""
    for idx in _progress_range(len(processed), batch_size, verbose):
        batch = processed[idx : idx + batch_size]
        vectors, prompt_tokens = backend.embeddings_with_usage(batch, model=model)
        price_acc[0] += prompt_tokens * PRICING.get(model, 0.0) / 1000000.0
        embeddings.extend(vectors)


class _BaseKLLMs:
    def __init__(
        self,
        backend: Union[str, Backend, None] = None,
        model: Optional[str] = None,
        timeout: Optional[float] = None,
        **backend_kwargs: Any,
    ):
        # When WE construct the backend from a name, the client-level model
        # must reach it: a local backend loads that model's weights at
        # construction. (Silently building the default model and labeling its
        # outputs with the requested name would serve the wrong weights.)
        if not isinstance(backend, Backend) and model is not None:
            backend_kwargs.setdefault("model", model)
        self._backend = resolve_backend(backend, **backend_kwargs)
        # Default request label follows the weights actually loaded — with no
        # explicit model, a local backend's own default must not be relabeled.
        self.default_model = (
            model or getattr(self._backend, "model_name", None) or "llama-3-8b"
        )
        # Client-level deadline default in seconds (the OpenAI client's
        # ``timeout=`` constructor knob); per-call ``timeout=`` overrides it.
        # None = unbounded, matching the reference's behavior of leaving
        # timeouts entirely to the SDK default.
        self.default_timeout = timeout

    @property
    def backend(self) -> Backend:
        return self._backend

    @property
    def client(self) -> Backend:
        """The underlying engine (the reference exposes its OpenAI client here)."""
        return self._backend

    # -- lifecycle --------------------------------------------------------
    def health(self) -> Any:
        """Serving-health snapshot from the backend: scheduler lifecycle
        state (including RECOVERING while the supervisor rebuilds a hung or
        poisoned engine), queue depth/weight, shed/OOM counters, breaker
        state, supervisor stats (epoch, hung launches, rebuilds, replay
        count), quarantine counters, and the loader's param summary (total
        bytes, dtype histogram, checksum) when a checkpoint is loaded."""
        return self._backend.health()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Gracefully stop serving: admission closes (new requests get a
        typed 503 ``ServerDrainingError``), in-flight and queued work
        finishes, the worker joins. Returns True when everything completed
        within ``timeout`` (None = the backend's configured default)."""
        if timeout is None:
            return self._backend.drain()
        return self._backend.drain(timeout=timeout)

    def close(self) -> None:
        """Drain and release the backend. Idempotent; also runs on
        context-manager exit."""
        self._backend.close()

    def get_embeddings(
        self,
        texts: List[str],
        model: str = "local",
        batch_size: int = 2048,
        verbose: bool = False,
    ) -> List[List[float]]:
        """Batched embeddings helper (reference `client.py:75-122`): validate the
        model, crop every text to the model's token cap, chunk by ``batch_size``,
        accumulate the billed price, report progress when ``verbose``."""
        model = _resolve_embedding_model(self._backend, model)
        max_tokens = MAX_TOKENS_PER_MODEL.get(model, MAX_TOKENS_PER_MODEL["local"])
        processed = self._backend.crop_texts(texts, max_tokens, model=model)

        embeddings: List[List[float]] = []
        price_acc = [0.0]
        _embed_batches(
            self._backend, processed, model, batch_size, verbose, embeddings, price_acc
        )
        if verbose:
            print(f"TOTAL PRICE: ${price_acc[0]:.6f}")
        return embeddings


class KLLMs(_BaseKLLMs):
    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self.chat = Chat(self)

    def __enter__(self) -> "KLLMs":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


class AsyncKLLMs(_BaseKLLMs):
    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self.chat = AsyncChat(self)

    async def __aenter__(self) -> "AsyncKLLMs":
        return self

    async def __aexit__(self, exc_type, exc, tb) -> None:
        import asyncio

        # drain() blocks on in-flight decodes; keep the event loop free.
        await asyncio.to_thread(self.close)

    async def async_get_embeddings(
        self,
        texts: List[str],
        model: str = "local",
        batch_size: int = 2048,
        verbose: bool = False,
    ) -> List[List[float]]:
        """Async embeddings with the reference's two-stage crop ladder
        (`client.py:125-196`): selectively crop only texts long enough to
        plausibly exceed the cap (cheap heuristic, off-thread), then on ANY
        failure re-crop everything and retry once."""
        import asyncio

        model = _resolve_embedding_model(self._backend, model)
        max_tokens = MAX_TOKENS_PER_MODEL.get(model, MAX_TOKENS_PER_MODEL["local"])
        backend = self._backend

        def selective_crop() -> List[str]:
            # ~3 chars/token lower bound: short strings can't exceed the cap.
            long_idx = [i for i, t in enumerate(texts) if len(t) * 3 > max_tokens]
            if not long_idx:
                return list(texts)
            cropped = backend.crop_texts([texts[i] for i in long_idx], max_tokens, model=model)
            out = list(texts)
            for i, c in zip(long_idx, cropped):
                out[i] = c
            return out

        def crop_all() -> List[str]:
            return backend.crop_texts(list(texts), max_tokens, model=model)

        price_acc = [0.0]
        embeddings: List[List[float]] = []

        def run_batches(processed: List[str]) -> List[List[float]]:
            embeddings.clear()
            _embed_batches(
                backend, processed, model, batch_size, verbose, embeddings, price_acc
            )
            return embeddings

        processed = await asyncio.to_thread(selective_crop)
        try:
            result = await asyncio.to_thread(run_batches, processed)
        except Exception as e:
            if verbose:
                print(f"Embedding request failed with error: {e}. Retrying with all strings cropped.")
            processed = await asyncio.to_thread(crop_all)
            result = await asyncio.to_thread(run_batches, processed)
        if verbose:
            print(f"TOTAL PRICE: ${price_acc[0]:.6f}")
        return result

    # Short alias kept for earlier adopters of this package.
    aget_embeddings = async_get_embeddings


class Chat:
    def __init__(self, wrapper: KLLMs):
        self._wrapper = wrapper
        self.completions = Completions(wrapper)


class AsyncChat:
    def __init__(self, wrapper: AsyncKLLMs):
        self._wrapper = wrapper
        self.completions = AsyncCompletions(wrapper)
