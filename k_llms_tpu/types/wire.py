"""Vendored OpenAI-compatible chat-completion wire types.

The reference (k-LLMs) subclasses pydantic models from the ``openai`` package
(`/root/reference/k_llms/types/completions.py:7`, `parsed.py:7`) and so depends on it
for types only. On TPU hosts we must run with zero OpenAI dependency, so this module
vendors a minimal-but-faithful pydantic replica of the wire types the reference's
surface uses: ``ChatCompletion``, ``Choice``, ``ChatCompletionMessage``,
``CompletionUsage`` (with token-detail subobjects), the logprob containers, and the
``Parsed*`` generics. Field names, defaults, and JSON layout match the OpenAI SDK so
serialized payloads are drop-in compatible.

If the real ``openai`` package is installed, ``k_llms_tpu.types`` prefers it (see
``k_llms_tpu/types/__init__.py``) — these models are the fallback.
"""

from __future__ import annotations

from typing import Any, Dict, Generic, List, Literal, Optional, TypeVar

from pydantic import BaseModel, ConfigDict


class _Model(BaseModel):
    """Base config mirroring openai._models.BaseModel: tolerate unknown fields."""

    model_config = ConfigDict(extra="allow")


class FunctionCall(_Model):
    arguments: str
    name: str


class Function(_Model):
    arguments: str
    name: str


class ChatCompletionMessageToolCall(_Model):
    id: str
    function: Function
    type: Literal["function"] = "function"


class TopLogprob(_Model):
    token: str
    bytes: Optional[List[int]] = None
    logprob: float


class ChatCompletionTokenLogprob(_Model):
    token: str
    bytes: Optional[List[int]] = None
    logprob: float
    top_logprobs: List[TopLogprob] = []


class ChoiceLogprobs(_Model):
    content: Optional[List[ChatCompletionTokenLogprob]] = None
    refusal: Optional[List[ChatCompletionTokenLogprob]] = None


class ChatCompletionMessage(_Model):
    content: Optional[str] = None
    refusal: Optional[str] = None
    role: Literal["assistant"] = "assistant"
    function_call: Optional[FunctionCall] = None
    tool_calls: Optional[List[ChatCompletionMessageToolCall]] = None


FinishReason = Literal["stop", "length", "tool_calls", "content_filter", "function_call"]


class Choice(_Model):
    finish_reason: FinishReason
    index: int
    logprobs: Optional[ChoiceLogprobs] = None
    message: ChatCompletionMessage


class PromptTokensDetails(_Model):
    audio_tokens: Optional[int] = None
    cached_tokens: Optional[int] = None


class CompletionTokensDetails(_Model):
    accepted_prediction_tokens: Optional[int] = None
    audio_tokens: Optional[int] = None
    reasoning_tokens: Optional[int] = None
    rejected_prediction_tokens: Optional[int] = None


class CompletionUsage(_Model):
    completion_tokens: int
    prompt_tokens: int
    total_tokens: int
    completion_tokens_details: Optional[CompletionTokensDetails] = None
    prompt_tokens_details: Optional[PromptTokensDetails] = None


class ChatCompletion(_Model):
    id: str
    choices: List[Choice]
    created: int
    model: str
    object: Literal["chat.completion"] = "chat.completion"
    service_tier: Optional[str] = None
    system_fingerprint: Optional[str] = None
    usage: Optional[CompletionUsage] = None


ContentType = TypeVar("ContentType")


class ParsedChatCompletionMessage(ChatCompletionMessage, Generic[ContentType]):
    parsed: Optional[ContentType] = None


class ParsedChoice(Choice, Generic[ContentType]):
    message: ParsedChatCompletionMessage[ContentType]


class ParsedChatCompletion(ChatCompletion, Generic[ContentType]):
    choices: List[ParsedChoice[ContentType]]  # type: ignore[assignment]


# Request-side aliases (the reference types these loosely; we accept plain dicts)
ChatCompletionMessageParam = Dict[str, Any]


# ---------------------------------------------------------------------------
# Typed request-lifecycle errors (OpenAI error shapes)
# ---------------------------------------------------------------------------
# The reference leans on the ``openai`` client's exception hierarchy
# (APITimeoutError, APIConnectionError, InternalServerError); a local engine
# must supply the same reliability contract itself. These carry the OpenAI
# wire error payload ({"error": {"message", "type", "code"}}) so a serving
# frontend can return them byte-compatibly.


class KLLMsError(Exception):
    """Base typed error; subclasses pin ``type``/``code``/``status_code`` to
    the OpenAI wire values for the failure class they represent."""

    type: str = "api_error"
    code: Optional[str] = None
    status_code: int = 500

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message

    def as_wire(self) -> Dict[str, Any]:
        """The OpenAI HTTP error body for this exception."""
        return {
            "error": {
                "message": self.message,
                "type": self.type,
                "code": self.code,
                "param": None,
            }
        }


class InvalidRequestError(KLLMsError):
    """Caller error: a parameter the backend cannot honor (e.g. ``stream=True``
    on a backend with no streaming path) or a malformed request body. OpenAI's
    ``invalid_request_error`` wire shape, HTTP 400. ``param`` names the
    offending field when known, so the wire body points at it."""

    type = "invalid_request_error"
    status_code = 400

    def __init__(self, message: str, param: Optional[str] = None, code: Optional[str] = None):
        super().__init__(message)
        self.param = param
        if code is not None:
            self.code = code

    def as_wire(self) -> Dict[str, Any]:
        body = super().as_wire()
        body["error"]["param"] = self.param
        return body


class RequestTimeoutError(KLLMsError):
    """Deadline exceeded — queued past its deadline, or cancelled at token
    granularity mid-decode (openai.APITimeoutError's wire shape)."""

    type = "timeout"
    code = "request_timeout"
    status_code = 408


class RequestCancelledError(KLLMsError):
    """Caller cancelled the request via its :class:`RequestBudget`."""

    type = "cancelled"
    code = "request_cancelled"
    status_code = 499  # nginx's client-closed-request; OpenAI has no cancel code


class BackendUnavailableError(KLLMsError):
    """The model engine cannot serve: circuit open, retries exhausted, or all
    samples lost (openai.InternalServerError / APIConnectionError class)."""

    type = "server_error"
    code = "backend_unavailable"
    status_code = 503


class NoHealthyReplicasError(BackendUnavailableError):
    """Every member of a :class:`ReplicaSet` is out of rotation (breaker open,
    supervisor RECOVERING, draining, or pulled after a dispatch failure) and no
    probe could bring one back. ``reasons`` maps replica id → why that member
    is unavailable, so the 503 body tells an operator which members to look at
    rather than just that the set is down."""

    code = "no_healthy_replicas"

    def __init__(self, message: str, reasons: Optional[Dict[str, str]] = None):
        super().__init__(message)
        self.reasons = dict(reasons or {})

    def as_wire(self) -> Dict[str, Any]:
        body = super().as_wire()
        body["error"]["replicas"] = dict(self.reasons)
        return body


class EngineHungError(BackendUnavailableError):
    """A device launch exceeded its wall-clock watchdog budget and was
    declared hung. The supervisor replays the work on a rebuilt engine, so
    callers normally never see this; it surfaces only when rebuild attempts
    are exhausted (then as the terminal member error). Subclasses
    BackendUnavailableError so every existing 503/breaker/retry treatment of
    an unavailable backend applies unchanged."""

    code = "engine_hung"


class CheckpointCorruptError(KLLMsError):
    """Weight integrity verification failed at load time: the checkpoint's
    bytes do not match its recorded checksums. Fail-fast and non-retryable —
    serving garbage weights is strictly worse than refusing to start."""

    type = "server_error"
    code = "checkpoint_corrupt"
    status_code = 500


class RateLimitError(KLLMsError):
    """Admission shed: the scheduler's queue is at its weight cap and this
    request was rejected instead of queued unboundedly (openai.RateLimitError's
    wire shape). ``retry_after`` is the scheduler's estimate, in seconds, of
    when the queue will have drained enough to admit work of this weight —
    serving frontends map it to the HTTP ``Retry-After`` header (OpenAI carries
    it as a header, not in the error body, so ``as_wire`` stays unchanged)."""

    type = "rate_limit_error"
    code = "rate_limit_exceeded"
    status_code = 429

    def __init__(self, message: str, retry_after: Optional[float] = None):
        super().__init__(message)
        self.retry_after = retry_after


class ServerDrainingError(KLLMsError):
    """The serving process is draining (or has stopped): admission is closed
    while in-flight work finishes. A load balancer should retry the request
    against another replica — hence 503, the standard drain signal."""

    type = "server_error"
    code = "server_draining"
    status_code = 503
