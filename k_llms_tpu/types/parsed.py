"""KLLMsParsedChatCompletion — consensus response contract for structured outputs.

Parity target: `/root/reference/k_llms/types/parsed.py:7-15`.
``choices[0].message.parsed`` holds the consensus object re-validated into the user's
``response_format`` model (`/root/reference/README.md:77-78`).
"""

from typing import Any, Dict, Optional

from pydantic import Field


def _parsed_chat_completion_base():
    try:  # pragma: no cover
        from openai.types.chat import ParsedChatCompletion  # type: ignore

        return ParsedChatCompletion
    except ImportError:
        from .wire import ParsedChatCompletion

        return ParsedChatCompletion


class KLLMsParsedChatCompletion(_parsed_chat_completion_base()):
    """Enhanced ParsedChatCompletion that includes likelihoods for consensus results."""

    likelihoods: Optional[Dict[str, Any]] = Field(
        default=None,
        description=(
            "Object defining the uncertainties of the fields extracted when using "
            "consensus. Follows the same structure as the extraction object."
        ),
    )

    degraded: Optional[Dict[str, Any]] = Field(
        default=None,
        description=(
            "Partial-failure marker: present when fewer than the requested n "
            "samples survived; see KLLMsChatCompletion.degraded."
        ),
    )
