"""Public return types: the k-LLMs response contract.

Prefers the real ``openai`` package's models when available (drop-in identical to the
reference, `/root/reference/k_llms/types/*.py`); otherwise uses the vendored replicas
in :mod:`k_llms_tpu.types.wire`.
"""

try:  # pragma: no cover - exercised only when openai is installed
    from openai.types.chat import ChatCompletion, ParsedChatCompletion  # type: ignore
    from openai.types.chat.chat_completion import Choice  # type: ignore
    from openai.types.chat import ChatCompletionMessage  # type: ignore
    from openai.types.chat.parsed_chat_completion import (  # type: ignore
        ParsedChatCompletionMessage,
        ParsedChoice,
    )
    from openai.types.chat.chat_completion import ChoiceLogprobs  # type: ignore
    from openai.types.completion_usage import (  # type: ignore
        CompletionTokensDetails,
        CompletionUsage,
        PromptTokensDetails,
    )

    HAVE_OPENAI = True
except ImportError:  # vendored fallback
    from .wire import (
        ChatCompletion,
        ChatCompletionMessage,
        Choice,
        ChoiceLogprobs,
        CompletionTokensDetails,
        CompletionUsage,
        ParsedChatCompletion,
        ParsedChatCompletionMessage,
        ParsedChoice,
        PromptTokensDetails,
    )

    HAVE_OPENAI = False

from .completions import KLLMsChatCompletion
from .parsed import KLLMsParsedChatCompletion

# Typed request-lifecycle errors are always ours (the openai package's
# exceptions wrap httpx responses we don't have), vendored in wire.py.
from .wire import (
    BackendUnavailableError,
    KLLMsError,
    RateLimitError,
    RequestCancelledError,
    RequestTimeoutError,
    ServerDrainingError,
)

__all__ = [
    "BackendUnavailableError",
    "KLLMsError",
    "RateLimitError",
    "RequestCancelledError",
    "RequestTimeoutError",
    "ServerDrainingError",
    "ChatCompletion",
    "ChatCompletionMessage",
    "Choice",
    "ChoiceLogprobs",
    "CompletionTokensDetails",
    "CompletionUsage",
    "HAVE_OPENAI",
    "KLLMsChatCompletion",
    "KLLMsParsedChatCompletion",
    "ParsedChatCompletion",
    "ParsedChatCompletionMessage",
    "ParsedChoice",
    "PromptTokensDetails",
]
