"""KLLMsChatCompletion — the consensus response contract.

Parity target: `/root/reference/k_llms/types/completions.py:7-15`. The contract
(`/root/reference/README.md:112-114`): ``choices[0]`` is the consolidated consensus
result, ``choices[1..n]`` are the n original samples, and ``likelihoods`` mirrors the
structure of the extracted object with per-field confidence scores.
"""

from typing import Any, Dict, Optional

from pydantic import Field


def _chat_completion_base():
    try:  # pragma: no cover
        from openai.types.chat import ChatCompletion  # type: ignore

        return ChatCompletion
    except ImportError:
        from .wire import ChatCompletion

        return ChatCompletion


class KLLMsChatCompletion(_chat_completion_base()):
    """Enhanced ChatCompletion that includes likelihoods for consensus results."""

    likelihoods: Optional[Dict[str, Any]] = Field(
        default=None,
        description=(
            "Object defining the uncertainties of the fields extracted when using "
            "consensus. Follows the same structure as the extraction object."
        ),
    )

    degraded: Optional[Dict[str, Any]] = Field(
        default=None,
        description=(
            "Partial-failure marker: present when fewer than the requested n "
            "samples survived (timeout, decode fault, failpoint). Carries "
            "requested/survived counts, the survival fraction the likelihoods "
            "were scaled by, and per-sample error records."
        ),
    )
