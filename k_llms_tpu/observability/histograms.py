"""Thread-safe log-bucketed latency histograms with declared vocabularies.

Same hygiene contract as ``EventCounters``: ``declared`` names the group's
histogram vocabulary (literals plus fnmatch wildcards), ``observe()`` raises
on anything outside it, and the ``counter-hygiene`` lint statically checks
every ``observe()`` literal against the same patterns — a typo'd histogram
that silently lands in its own family is invisible to every dashboard that
queries the real name.

Buckets are log-spaced seconds shared across families (1ms → 60s), rendered
on ``/metrics`` in Prometheus histogram exposition (cumulative ``_bucket``
counts, ``_sum``, ``_count``). Exactly-declared families export even at zero
observations, so the scrape surface is stable from the first poll.
"""

from __future__ import annotations

import bisect
import fnmatch
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..analysis.lockcheck import make_lock

#: Log-spaced bucket upper bounds in seconds (1-2.5-5 decades, 1ms → 60s).
#: The +Inf bucket is implicit: its cumulative count is the sample count.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class LatencyHistograms:
    """A group of named latency histograms sharing one bucket layout.

    ``observe(name, seconds)`` is cheap enough for the scheduler worker and
    the continuous loop's host bookkeeping (a bisect + three dict writes
    under a leaf lock); ``snapshot()`` returns cumulative bucket counts
    ready for Prometheus exposition."""

    def __init__(
        self,
        declared: Optional[Sequence[str]] = None,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> None:
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds or any(b <= 0 for b in bounds) or len(set(bounds)) != len(bounds):
            raise ValueError("histogram buckets must be distinct positive bounds")
        self._lock = make_lock("observability.histograms")
        self.buckets = bounds
        self.declared: Tuple[str, ...] = tuple(declared or ())
        self._exact = {p for p in self.declared if "*" not in p and "?" not in p}
        self._globs = [p for p in self.declared if p not in self._exact]
        # Exact families pre-exist so /metrics exports them at zero samples.
        self._counts: Dict[str, List[int]] = {
            name: [0] * len(bounds) for name in sorted(self._exact)
        }
        self._sums: Dict[str, float] = {}
        self._totals: Dict[str, int] = {}

    def _check_declared(self, name: str) -> None:
        if not self.declared or name in self._exact:
            return
        if any(fnmatch.fnmatch(name, p) for p in self._globs):
            return
        raise ValueError(
            f"histogram {name!r} is not declared for this group "
            f"(declared: {sorted(self.declared)})"
        )

    def observe(self, name: str, seconds: float) -> None:
        self._check_declared(name)
        v = max(0.0, float(seconds))
        with self._lock:
            counts = self._counts.get(name)
            if counts is None:
                counts = self._counts[name] = [0] * len(self.buckets)
            i = bisect.bisect_left(self.buckets, v)
            if i < len(counts):
                counts[i] += 1
            self._sums[name] = self._sums.get(name, 0.0) + v
            self._totals[name] = self._totals.get(name, 0) + 1

    def count(self, name: str) -> int:
        with self._lock:
            return self._totals.get(name, 0)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-family ``{"buckets": [(le, cumulative_count)...], "sum": s,
        "count": c}`` — bucket counts already cumulative and monotone; the
        +Inf bucket is ``count``."""
        with self._lock:
            out: Dict[str, Dict[str, Any]] = {}
            for name in sorted(self._counts):
                cum: List[Tuple[float, int]] = []
                acc = 0
                for bound, c in zip(self.buckets, self._counts[name]):
                    acc += c
                    cum.append((bound, acc))
                out[name] = {
                    "buckets": cum,
                    "sum": self._sums.get(name, 0.0),
                    "count": self._totals.get(name, 0),
                }
            return out

    def reset(self) -> None:
        with self._lock:
            for counts in self._counts.values():
                for i in range(len(counts)):
                    counts[i] = 0
            self._sums.clear()
            self._totals.clear()


#: Process-wide latency histograms for the serving stack, surfaced on
#: ``/metrics`` as ``kllms_<family>_seconds`` (dots become underscores):
#: request.e2e — full request wall time, observed when a trace finishes;
#: request.ttft — time to first streamed token, observed at the first delta
#: a ChatCompletionStream emits; scheduler.queue_wait — admission-to-dequeue
#: wait, observed at both the coalescing scheduler's group pop and the
#: continuous loop's slot admission; continuous.step — one continuous-loop
#: step's host wall time around the (possibly watchdogged) device dispatch;
#: engine.decode_launch — one coalesced decode launch (the paged-attention
#: fused path included), observed around the supervised generate_many call;
#: consensus.consolidate — consensus consolidation wall time. All observes
#: are host-side wall clock — never inside jitted step programs.
#:
#: The ``.*`` wildcard families are the per-tenant label sets (ISSUE 16):
#: ``request.e2e.<tenant>`` / ``request.ttft.<tenant>`` /
#: ``scheduler.queue_wait.<tenant>`` record the same observation a second
#: time under the request's tenant, and ``/metrics`` renders them as one
#: labeled family per base name (``kllms_request_e2e_by_tenant_seconds``
#: with a ``tenant`` label) so per-tenant SLO compliance is scrapeable
#: without pre-registering tenant names.
#: The batch-lane families (ISSUE 17): ``batch.item`` — one offline item's
#: end-to-end wall time through the lane (dequeue → committed output
#: segment); ``batch.job_e2e`` — a whole job from durable submission to
#: terminal status, wall clock, spanning restarts (the journal carries
#: ``created_at``).
#: The chunked-prefill family (ISSUE 18): ``continuous.prefill_chunk`` — one
#: interleaved prompt-chunk dispatch's host wall time (device step + paged
#: scatter + sync), observed per chunk by the continuous loop; compare its
#: max against ``continuous.step`` p50 to verify long admissions no longer
#: stall in-flight decode rows.
LATENCY = LatencyHistograms(declared=(
    "request.e2e",
    "request.ttft",
    "scheduler.queue_wait",
    "continuous.step",
    "continuous.prefill_chunk",
    "engine.decode_launch",
    "consensus.consolidate",
    "batch.item",
    "batch.job_e2e",
    "request.e2e.*",
    "request.ttft.*",
    "scheduler.queue_wait.*",
))
