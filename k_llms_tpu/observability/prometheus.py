"""Prometheus text exposition (format 0.0.4) renderer for ``/metrics``.

Proper exposition hygiene, not a bare text dump: every metric family gets
``# HELP``/``# TYPE`` lines, label values are escaped per the format spec
(backslash, double-quote, newline), and histograms render the full
``_bucket``/``_sum``/``_count`` triple with cumulative counts and the
mandatory ``+Inf`` bucket. The serving app builds family dicts with the
helpers here and renders once per scrape — no client library dependency.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Mapping, Tuple

FamilyDict = Dict[str, Any]


def escape_label_value(value: Any) -> str:
    """Label-value escaping per the 0.0.4 text format: backslash first, then
    double-quote and newline."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def escape_help(text: str) -> str:
    """HELP-line escaping: only backslash and newline are special."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def format_value(value: Any) -> str:
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    return repr(float(value))


def format_bound(bound: float) -> str:
    """A bucket bound as Prometheus expects it: trimmed decimal, no
    float-repr noise (0.0025 stays "0.0025")."""
    text = format(float(bound), ".12g")
    return text


def _labels_text(labels: Mapping[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in labels.items()
    )
    return "{" + inner + "}"


def counter_family(
    name: str, help_text: str, samples: Iterable[Tuple[Mapping[str, Any], Any]]
) -> FamilyDict:
    return {
        "name": name,
        "type": "counter",
        "help": help_text,
        "samples": [("", dict(labels), value) for labels, value in samples],
    }


def gauge_family(name: str, help_text: str, value: Any) -> FamilyDict:
    return {
        "name": name,
        "type": "gauge",
        "help": help_text,
        "samples": [("", {}, value)],
    }


def histogram_family(name: str, help_text: str, snap: Mapping[str, Any]) -> FamilyDict:
    """A histogram family from a ``LatencyHistograms.snapshot()`` entry:
    cumulative ``_bucket`` samples (``+Inf`` = count), ``_sum``, ``_count``."""
    samples: List[Tuple[str, Dict[str, Any], Any]] = []
    for bound, cumulative in snap["buckets"]:
        samples.append(("_bucket", {"le": format_bound(bound)}, cumulative))
    samples.append(("_bucket", {"le": "+Inf"}, snap["count"]))
    samples.append(("_sum", {}, snap["sum"]))
    samples.append(("_count", {}, snap["count"]))
    return {
        "name": name,
        "type": "histogram",
        "help": help_text,
        "samples": samples,
    }


def labeled_histogram_family(
    name: str,
    help_text: str,
    snaps: Mapping[str, Mapping[str, Any]],
    label: str = "tenant",
) -> FamilyDict:
    """One histogram family carrying a label dimension: each entry of
    ``snaps`` (label value → ``LatencyHistograms.snapshot()`` entry) emits a
    full ``_bucket``/``_sum``/``_count`` triple with ``label`` merged into
    every sample. Prometheus requires one HELP/TYPE per family, so per-tenant
    histograms must share a family rather than minting one per tenant; label
    values are escaped at render time (hostile tenant ids included)."""
    samples: List[Tuple[str, Dict[str, Any], Any]] = []
    for value in sorted(snaps):
        snap = snaps[value]
        for bound, cumulative in snap["buckets"]:
            samples.append(
                ("_bucket", {label: value, "le": format_bound(bound)}, cumulative)
            )
        samples.append(("_bucket", {label: value, "le": "+Inf"}, snap["count"]))
        samples.append(("_sum", {label: value}, snap["sum"]))
        samples.append(("_count", {label: value}, snap["count"]))
    return {
        "name": name,
        "type": "histogram",
        "help": help_text,
        "samples": samples,
    }


def render_families(families: Iterable[FamilyDict]) -> str:
    """The full exposition body. Families render in the order given; each
    emits HELP and TYPE even when it currently has no samples, so the scrape
    surface (and the scrape-validity test) is stable."""
    lines: List[str] = []
    for fam in families:
        lines.append(f"# HELP {fam['name']} {escape_help(fam['help'])}")
        lines.append(f"# TYPE {fam['name']} {fam['type']}")
        for suffix, labels, value in fam["samples"]:
            lines.append(
                f"{fam['name']}{suffix}{_labels_text(labels)} {format_value(value)}"
            )
    return "\n".join(lines) + "\n"
