"""Request-scoped tracing: trace_id/span_id context for the serving stack.

One :class:`RequestTrace` rides a request from the HTTP front door (or an
in-process ``create()`` call) through scheduler admission, coalescing or
continuous-loop decode, and consensus consolidation. Trace context is
ingested from a W3C ``traceparent`` header when the caller sends one and
generated otherwise; propagation is a :mod:`contextvars` variable, which
``asyncio.to_thread`` copies into the worker thread running the client call,
plus explicit capture at the two plain-``threading`` boundaries (scheduler
``_Item`` and continuous-loop ``_SlotRequest`` hold the submitting thread's
trace; the stream sink thread re-enters it via :func:`use_trace`).

Phases accumulate (``+=``) into one duration table, so a watchdog
rebuild+replay extends the SAME trace — one coherent record with a
``replayed`` annotation rather than two half-traces. Everything here is
host-side wall clock: no device syncs, nothing inside jitted step programs.

Tracing must never fail a request: the ``serving.trace`` failpoint's
``drop`` action (and any unexpected error while starting a trace) degrades
the tracer to :data:`NOOP_TRACE`, whose spans are free and which is never
flight-recorded.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import re
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..analysis.lockcheck import make_lock
from ..reliability import failpoints as _failpoints
from .flight import FLIGHT_RECORDER, FlightRecorder
from .histograms import LATENCY, LatencyHistograms

_TRACEPARENT_RE = re.compile(
    r"^(?P<version>[0-9a-f]{2})-(?P<trace_id>[0-9a-f]{32})-"
    r"(?P<span_id>[0-9a-f]{16})-(?P<flags>[0-9a-f]{2})$"
)

#: Per-trace span cap: a pathological request (thousands of coalesced decode
#: launches) keeps its aggregate durations but stops growing the span list.
MAX_SPANS = 128


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str, str]]:
    """``(trace_id, parent_span_id, flags)`` from a W3C traceparent header,
    or None when absent/malformed (all-zero ids and version ff are invalid
    per spec, and a bad header must not fail the request)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    trace_id = m.group("trace_id")
    span_id = m.group("span_id")
    if (
        m.group("version") == "ff"
        or trace_id == "0" * 32
        or span_id == "0" * 16
    ):
        return None
    return trace_id, span_id, m.group("flags")


def format_traceparent(trace_id: str, span_id: str, flags: str = "01") -> str:
    return f"00-{trace_id}-{span_id}-{flags}"


class Span:
    """One recorded phase occurrence: name + offset from trace start +
    duration, with its own span_id parented on the trace's root span."""

    __slots__ = ("name", "span_id", "parent_id", "start_s", "duration_s")

    def __init__(
        self, name: str, span_id: str, parent_id: str, start_s: float, duration_s: float
    ) -> None:
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_s = start_s
        self.duration_s = duration_s

    def as_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_s": round(self.start_s, 6),
            "duration_s": round(self.duration_s, 6),
        }


class RequestTrace:
    """Thread-safe per-request trace: aggregated phase durations (the
    ``KLLMS_TRACE=1`` ``timings`` payload), a bounded span list, and
    free-form annotations (``replayed``, ``quarantined_rows``...).

    ``phase()`` keeps the old two-phase ``Trace`` API so existing call sites
    and tests hold; mutation is guarded by a lockcheck leaf lock because the
    stream sink thread and the caller can time phases concurrently."""

    def __init__(
        self,
        trace_id: Optional[str] = None,
        parent_span_id: Optional[str] = None,
        flags: str = "01",
    ) -> None:
        self._lock = make_lock("observability.trace")
        self.trace_id = trace_id or _new_trace_id()
        self.span_id = _new_span_id()
        self.parent_span_id = parent_span_id
        self.flags = flags
        self.started_at = time.time()
        self._t0 = time.monotonic()
        self.durations: Dict[str, float] = {}
        self.spans: List[Span] = []
        self.annotations: Dict[str, Any] = {}
        self._finished = False

    @property
    def noop(self) -> bool:
        return False

    def traceparent(self) -> str:
        """The outgoing W3C header for this trace's root span."""
        return format_traceparent(self.trace_id, self.span_id, self.flags)

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.monotonic() - self._t0
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_phase(name, time.perf_counter() - t0, start_offset_s=start)

    def add_phase(
        self, name: str, duration_s: float, start_offset_s: Optional[float] = None
    ) -> None:
        """Accumulate a phase duration (and one span) measured externally —
        the thread-boundary form of ``phase()`` for the scheduler worker and
        the continuous loop, where the timed region isn't a ``with`` block
        on the trace owner's thread."""
        if start_offset_s is None:
            start_offset_s = max(0.0, time.monotonic() - self._t0 - duration_s)
        with self._lock:
            self.durations[name] = self.durations.get(name, 0.0) + duration_s
            if len(self.spans) < MAX_SPANS:
                self.spans.append(
                    Span(name, _new_span_id(), self.span_id, start_offset_s, duration_s)
                )

    def annotate(self, key: str, value: Any = True) -> None:
        with self._lock:
            self.annotations[key] = value

    def bump(self, key: str, n: int = 1) -> None:
        """Increment a numeric annotation (replay/quarantine tallies)."""
        with self._lock:
            prev = self.annotations.get(key)
            base = prev if isinstance(prev, (int, float)) and not isinstance(prev, bool) else 0
            self.annotations[key] = base + n

    def elapsed_s(self) -> float:
        return time.monotonic() - self._t0

    def mark_finished(self) -> bool:
        """First caller wins — the idempotence behind "exactly one flight
        record per request" even when both the HTTP front door and an inner
        owner try to finish."""
        with self._lock:
            if self._finished:
                return False
            self._finished = True
            return True

    def as_dict(self) -> Dict[str, float]:
        with self._lock:
            return {k: round(v, 6) for k, v in self.durations.items()}

    def spans_as_dicts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [s.as_dict() for s in self.spans]

    def annotations_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self.annotations)


class NoopTrace:
    """Same surface as :class:`RequestTrace`, no state and no cost: the
    degraded mode behind the ``serving.trace=drop`` failpoint. Never
    finished, never flight-recorded; the request completes untouched."""

    trace_id = ""
    span_id = ""
    parent_span_id = None
    flags = "00"
    started_at = 0.0
    durations: Dict[str, float] = {}
    spans: List[Span] = []
    annotations: Dict[str, Any] = {}

    @property
    def noop(self) -> bool:
        return True

    def traceparent(self) -> str:
        return ""

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        yield

    def add_phase(self, name: str, duration_s: float, start_offset_s: Optional[float] = None) -> None:
        pass

    def annotate(self, key: str, value: Any = True) -> None:
        pass

    def bump(self, key: str, n: int = 1) -> None:
        pass

    def elapsed_s(self) -> float:
        return 0.0

    def mark_finished(self) -> bool:
        return False

    def as_dict(self) -> Dict[str, float]:
        return {}

    def spans_as_dicts(self) -> List[Dict[str, Any]]:
        return []

    def annotations_snapshot(self) -> Dict[str, Any]:
        return {}


#: Shared degraded-mode trace (stateless, so one instance serves everyone).
NOOP_TRACE = NoopTrace()

_current: "contextvars.ContextVar[Optional[RequestTrace]]" = contextvars.ContextVar(
    "kllms_request_trace", default=None
)


def current_trace() -> Optional[RequestTrace]:
    """The trace bound to this thread/task context, if any."""
    return _current.get()


@contextlib.contextmanager
def use_trace(trace: Optional[RequestTrace]) -> Iterator[Optional[RequestTrace]]:
    """Bind ``trace`` as the current context for the block (used by the HTTP
    front door and by worker threads re-entering a captured trace)."""
    token = _current.set(trace)
    try:
        yield trace
    finally:
        _current.reset(token)


class Tracer:
    """Starts, propagates, and finishes request traces; finishing observes
    end-to-end latency and hands the record to the flight recorder."""

    def __init__(
        self,
        recorder: Optional[FlightRecorder] = None,
        latency: Optional[LatencyHistograms] = None,
    ) -> None:
        self._recorder = recorder
        self._latency = latency

    def start(self, traceparent: Optional[str] = None) -> RequestTrace:
        """A new trace adopting the caller's W3C context when present.
        Degrades to :data:`NOOP_TRACE` under the ``serving.trace`` drop
        failpoint or any unexpected error — tracing never fails a request."""
        try:
            spec = _failpoints.fire("serving.trace")
            if spec is not None and spec.action == "drop":
                return NOOP_TRACE
            parsed = parse_traceparent(traceparent)
            if parsed is not None:
                trace_id, parent_span_id, flags = parsed
                return RequestTrace(
                    trace_id=trace_id, parent_span_id=parent_span_id, flags=flags
                )
            return RequestTrace()
        except Exception:
            return NOOP_TRACE

    def current_or_start(self) -> Tuple[RequestTrace, bool]:
        """The context's trace, or a fresh one. The bool is ownership: the
        component that created the trace is the one that must finish it."""
        cur = current_trace()
        if cur is not None:
            return cur, False
        return self.start(), True

    def finish(
        self,
        trace: Optional[RequestTrace],
        *,
        route: str,
        status: Any,
        n: Optional[int] = None,
        error: Optional[BaseException] = None,
        tenant: Optional[str] = None,
    ) -> Optional[Dict[str, Any]]:
        """Close a trace exactly once: observe e2e latency, flight-record.
        Re-finishing (or finishing a noop trace) is a no-op, which is what
        makes "exactly one record per request" hold across owners. When the
        request carried a tenant, the same e2e lands a second time in the
        per-tenant family (``request.e2e.<tenant>``) for the labeled
        ``/metrics`` exposition."""
        if trace is None or trace.noop or not trace.mark_finished():
            return None
        e2e = trace.elapsed_s()
        if self._latency is not None:
            self._latency.observe("request.e2e", e2e)
            if tenant:
                self._latency.observe(f"request.e2e.{tenant}", e2e)
        record: Dict[str, Any] = {
            "trace_id": trace.trace_id,
            "span_id": trace.span_id,
            "parent_span_id": trace.parent_span_id,
            "route": route,
            "status": status,
            "n": n,
            "started_at": round(trace.started_at, 3),
            "duration_s": round(e2e, 6),
            "phases": trace.as_dict(),
            "annotations": trace.annotations_snapshot(),
        }
        if tenant:
            record["tenant"] = tenant
        if error is not None:
            record["error"] = f"{type(error).__name__}: {error}"[:500]
        if self._recorder is not None:
            self._recorder.record(record)
        return record


#: Process-wide tracer wired to the process flight recorder and latency
#: histograms — the one the serving stack uses.
TRACER = Tracer(recorder=FLIGHT_RECORDER, latency=LATENCY)
