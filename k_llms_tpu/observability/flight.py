"""Flight recorder: a bounded ring buffer of finished request records.

Every finished trace (completed or failed) lands here as a plain dict —
trace_id, route, n, status, phase durations, and the recovery/quarantine
annotations the PR-13 fault domains stamp on the trace. The ring is the
post-incident "what were the last N requests doing" view served at
``GET /debug/requests`` (off by default; ``BackendConfig.debug_endpoints``).

Bounded by design: a deque with ``maxlen`` so sustained traffic costs O(1)
memory and the recorder can never be the thing that falls over during the
incident it exists to explain.
"""

from __future__ import annotations

import collections
from typing import Any, Deque, Dict, List, Optional

from ..analysis.lockcheck import make_lock

#: Default ring capacity: enough recent history to cover a watchdog rebuild
#: plus the traffic around it, small enough to be always-on.
DEFAULT_CAPACITY = 256


class FlightRecorder:
    """Thread-safe bounded ring of request records (newest kept)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        if capacity <= 0:
            raise ValueError("flight recorder capacity must be positive")
        self._lock = make_lock("observability.flight")
        self.capacity = capacity
        self._ring: Deque[Dict[str, Any]] = collections.deque(maxlen=capacity)
        self._total = 0

    def record(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(dict(rec))
            self._total += 1

    def snapshot(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Newest-first copies of the held records."""
        with self._lock:
            items = [dict(r) for r in self._ring]
        items.reverse()
        return items[:limit] if limit is not None else items

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "held": len(self._ring),
                "recorded_total": self._total,
            }

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._total = 0


#: Process-wide flight recorder the tracer writes into.
FLIGHT_RECORDER = FlightRecorder()
