"""Observability layer for the serving stack (see README "Observability").

Three pieces, re-exported through ``utils/observability.py`` for the rest of
the package:

- :mod:`.trace` — ``Tracer``/``Span`` request tracing with W3C
  ``traceparent`` ingestion and contextvar propagation;
- :mod:`.histograms` — declared-vocabulary log-bucketed latency histograms
  (``EventCounters`` hygiene contract, ``counter-hygiene`` lint enforced);
- :mod:`.flight` — the bounded flight recorder behind ``/debug/requests``;
- :mod:`.prometheus` — text-exposition (0.0.4) rendering for ``/metrics``.
"""

from .flight import DEFAULT_CAPACITY, FLIGHT_RECORDER, FlightRecorder
from .histograms import DEFAULT_BUCKETS, LATENCY, LatencyHistograms
from .prometheus import (
    counter_family,
    escape_help,
    escape_label_value,
    format_bound,
    format_value,
    gauge_family,
    histogram_family,
    render_families,
)
from .trace import (
    MAX_SPANS,
    NOOP_TRACE,
    NoopTrace,
    RequestTrace,
    Span,
    TRACER,
    Tracer,
    current_trace,
    format_traceparent,
    parse_traceparent,
    use_trace,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "DEFAULT_CAPACITY",
    "FLIGHT_RECORDER",
    "FlightRecorder",
    "LATENCY",
    "LatencyHistograms",
    "MAX_SPANS",
    "NOOP_TRACE",
    "NoopTrace",
    "RequestTrace",
    "Span",
    "TRACER",
    "Tracer",
    "counter_family",
    "current_trace",
    "escape_help",
    "escape_label_value",
    "format_bound",
    "format_value",
    "format_traceparent",
    "gauge_family",
    "histogram_family",
    "parse_traceparent",
    "render_families",
    "use_trace",
]
