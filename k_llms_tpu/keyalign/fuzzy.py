"""Fuzzy key selection: canonicalized scalars (rounded numerics, normalized
strings), preferred over standard selection iff stability strictly improves.

Behavioral spec: `/root/reference/k_llms/utils/fuzzy_key_selection.py` —
canonicalization :37-52, fuzzy cascade :100-157 (served here by the shared
parametrized funnel in selection.py), comparison/decision :175-232 — pinned by
the differential oracle in ``tests/test_keyalign.py``.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional

from . import selection
from .selection import CascadeConfig, KeyMetrics


def canonicalize_scalar(value: Any, numeric_round_decimals: int = 2) -> Any:
    """Numbers rounded to N decimals; strings lower/trim/collapse; rest as-is."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        try:
            quantized = round(float(value), numeric_round_decimals)
        except Exception:
            quantized = value
        return quantized
    return selection.normalize_scalar(value)


@dataclass(frozen=True)
class SelectionComparison:
    """Which strategy won: "normal" | "fuzzy"."""

    normal_best: Optional[KeyMetrics] = None
    fuzzy_best: Optional[KeyMetrics] = None
    chosen: str = "normal"


def select_best_keys_with_fuzzy_fallback(
    extractions: List[Dict[str, Any]],
    cascade_cfg: CascadeConfig = CascadeConfig(),
    list_key: Optional[str] = None,
    fuzzy_numeric_round_decimals: int = 2,
    enable_fuzzy_fallback: bool = True,
    prefer_fuzzy_if_better: bool = True,
) -> SelectionComparison:
    """Run both selectors and pick one: exact wins unless fuzzy exists and
    strictly improves the stability tuple (or exact failed entirely)."""

    def attempt(run):
        try:
            return run()
        except ValueError:
            return None

    exact = attempt(
        lambda: selection.select_best_keys(
            extractions, cascade_cfg=cascade_cfg, list_key=list_key
        ).best_single
    )

    fuzzy = None
    if enable_fuzzy_fallback:
        paths = selection.discover_scalar_paths(extractions, list_key=list_key)
        if paths:
            fuzzy = attempt(
                lambda: selection.cascade_select_keys(
                    extractions,
                    paths,
                    cascade_cfg,
                    list_key=list_key,
                    canonicalize=partial(
                        canonicalize_scalar, numeric_round_decimals=fuzzy_numeric_round_decimals
                    ),
                ).final_best
            )

    if exact is None and fuzzy is None:
        raise ValueError("No keys pass Stage 0 (normal or fuzzy)")
    if exact is None:
        return SelectionComparison(fuzzy_best=fuzzy, chosen="fuzzy")
    if fuzzy is None:
        return SelectionComparison(normal_best=exact)
    take_fuzzy = prefer_fuzzy_if_better and fuzzy.stability > exact.stability
    return SelectionComparison(
        normal_best=exact, fuzzy_best=fuzzy, chosen="fuzzy" if take_fuzzy else "normal"
    )
