"""Fuzzy key selection: canonicalized scalars (rounded numerics, normalized
strings), preferred over standard selection iff stability strictly improves.

Parity target: `/root/reference/k_llms/utils/fuzzy_key_selection.py` —
canonicalization :37-52, fuzzy cascade :100-157 (here the shared parametrized
funnel from selection.py), comparison/decision :175-232.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from pydantic import BaseModel, ConfigDict

from .selection import (
    CascadeConfig,
    KeyMetrics,
    cascade_select_keys,
    discover_scalar_paths,
    normalize_scalar,
    select_best_keys,
    stability_tuple,
)


def canonicalize_scalar(value: Any, numeric_round_decimals: int = 2) -> Any:
    """Numbers rounded to N decimals; strings lower/trim/collapse; rest as-is."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        try:
            return round(float(value), numeric_round_decimals)
        except Exception:
            return value
    if isinstance(value, str):
        return normalize_scalar(value)
    return value


class SelectionComparison(BaseModel):
    """Which strategy won: "normal" | "fuzzy"."""

    model_config = ConfigDict(frozen=True)

    normal_best: Optional[KeyMetrics]
    fuzzy_best: Optional[KeyMetrics]
    chosen: str


def select_best_keys_with_fuzzy_fallback(
    extractions: List[Dict[str, Any]],
    cascade_cfg: CascadeConfig = CascadeConfig(),
    list_key: Optional[str] = None,
    fuzzy_numeric_round_decimals: int = 2,
    enable_fuzzy_fallback: bool = True,
    prefer_fuzzy_if_better: bool = True,
) -> SelectionComparison:
    normal_best: Optional[KeyMetrics] = None
    try:
        normal_best = select_best_keys(
            extractions, cascade_cfg=cascade_cfg, list_key=list_key
        ).best_single
    except ValueError:
        normal_best = None

    fuzzy_best: Optional[KeyMetrics] = None
    if enable_fuzzy_fallback:
        candidates = discover_scalar_paths(extractions, list_key=list_key)
        if candidates:
            try:
                fuzzy_best = cascade_select_keys(
                    extractions,
                    candidates,
                    cascade_cfg,
                    list_key=list_key,
                    canonicalize=lambda v: canonicalize_scalar(
                        v, fuzzy_numeric_round_decimals
                    ),
                ).final_best
            except ValueError:
                fuzzy_best = None

    if normal_best is None and fuzzy_best is None:
        raise ValueError("No keys pass Stage 0 (normal or fuzzy)")

    if normal_best is not None and (not enable_fuzzy_fallback or fuzzy_best is None):
        return SelectionComparison(normal_best=normal_best, fuzzy_best=None, chosen="normal")

    if normal_best is None:
        return SelectionComparison(normal_best=None, fuzzy_best=fuzzy_best, chosen="fuzzy")

    if prefer_fuzzy_if_better and stability_tuple(fuzzy_best) > stability_tuple(normal_best):
        return SelectionComparison(
            normal_best=normal_best, fuzzy_best=fuzzy_best, chosen="fuzzy"
        )
    return SelectionComparison(normal_best=normal_best, fuzzy_best=fuzzy_best, chosen="normal")
