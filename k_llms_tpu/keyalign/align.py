"""Key-based recursive alignment engine.

Parity target: `/root/reference/k_llms/utils/key_based_alignment.py` —
``_get_key_tuple`` :47-68 (NB: matches on RAW values; only key *selection* uses
normalization), ``_align_lists_by_key`` :71-151 (order from the longest source,
then remaining keys sorted), the recursive core :156-347 (zip fallback for
scalar lists :324-345), per-source view projection :474-516, and the public
``recursive_align`` :350-431 whose signature matches the similarity aligner so
it can swap in at the documented point (`consolidation.py:22`).
"""

from __future__ import annotations

import logging
from copy import deepcopy
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .fuzzy import select_best_keys_with_fuzzy_fallback
from .selection import CascadeConfig, select_best_keys

logger = logging.getLogger(__name__)


def _get_key_tuple(obj: Dict[str, Any], paths: Tuple[str, ...]) -> Optional[Tuple[Any, ...]]:
    """Raw (un-normalized) key tuple; None if any path fails to resolve."""
    values = []
    for path in paths:
        current: Any = obj
        for part in path.split("."):
            if isinstance(current, dict) and part in current:
                current = current[part]
            else:
                return None
        if current is None or isinstance(current, (dict, list)):
            return None
        values.append(current)
    return tuple(values)


def _align_lists_by_key(
    lists_to_align: Sequence[Optional[List[Dict[str, Any]]]],
    key_paths: Tuple[str, ...],
) -> Tuple[List[List[Optional[Dict[str, Any]]]], List[List[Optional[int]]]]:
    """Rows = key tuples (ordered by the longest source list, then sorted
    leftovers); columns = sources. Returns (aligned_rows, original_indices)."""
    if not any(lists_to_align):
        return [], []

    all_key_tuples: set = set()
    indexes: List[Dict[Tuple[Any, ...], int]] = []
    for source_list in lists_to_align:
        mapping: Dict[Tuple[Any, ...], int] = {}
        if isinstance(source_list, list):
            for i, item in enumerate(source_list):
                if isinstance(item, dict):
                    key_tuple = _get_key_tuple(item, key_paths)
                    if key_tuple is not None and key_tuple not in mapping:
                        mapping[key_tuple] = i
                        all_key_tuples.add(key_tuple)
        indexes.append(mapping)

    def _safe_len(source_list) -> int:
        return len(source_list) if isinstance(source_list, list) else 0

    best_source_idx = max(range(len(lists_to_align)), key=lambda i: _safe_len(lists_to_align[i]))
    best_source_list = lists_to_align[best_source_idx]

    ordered_keys: List[Tuple[Any, ...]] = []
    seen_keys: set = set()
    if isinstance(best_source_list, list):
        for item in best_source_list:
            if isinstance(item, dict):
                key_tuple = _get_key_tuple(item, key_paths)
                if key_tuple is not None and key_tuple not in seen_keys:
                    ordered_keys.append(key_tuple)
                    seen_keys.add(key_tuple)
    ordered_keys.extend(sorted(all_key_tuples - seen_keys))

    aligned_rows: List[List[Optional[Dict[str, Any]]]] = []
    original_indices: List[List[Optional[int]]] = []
    for key_tuple in ordered_keys:
        row: List[Optional[Dict[str, Any]]] = []
        indices_row: List[Optional[int]] = []
        for source_idx, source_list in enumerate(lists_to_align):
            original_idx = indexes[source_idx].get(key_tuple)
            if original_idx is not None and isinstance(source_list, list):
                row.append(source_list[original_idx])
                indices_row.append(original_idx)
            else:
                row.append(None)
                indices_row.append(None)
        aligned_rows.append(row)
        original_indices.append(indices_row)

    return aligned_rows, original_indices


def _select_key_paths(
    lists: List[List[Any]], cascade_cfg: CascadeConfig
) -> Optional[Tuple[str, ...]]:
    """Standard selection (composite-aware) first; fuzzy preferred when it
    improves stability; fuzzy-only as last resort."""
    dummy_extractions = [{"items": lst} for lst in lists]
    try:
        result = select_best_keys(dummy_extractions, list_key="items", cascade_cfg=cascade_cfg)
        use_composite = (
            result.best_composite is not None
            and result.best_composite.score_tuple > result.best_single.score_tuple
        )
        standard_paths = (
            result.best_composite.path if use_composite else result.best_single.path
        )
        try:
            comp = select_best_keys_with_fuzzy_fallback(
                dummy_extractions,
                cascade_cfg=cascade_cfg,
                list_key="items",
                fuzzy_numeric_round_decimals=2,
                enable_fuzzy_fallback=True,
                prefer_fuzzy_if_better=True,
            )
            if comp.chosen == "fuzzy" and comp.fuzzy_best is not None:
                logger.debug("key-select: fuzzy path %s", comp.fuzzy_best.path)
                return comp.fuzzy_best.path
        except Exception:
            pass
        logger.debug("key-select: standard path %s", standard_paths)
        return standard_paths
    except ValueError:
        try:
            comp = select_best_keys_with_fuzzy_fallback(
                dummy_extractions,
                cascade_cfg=cascade_cfg,
                list_key="items",
                fuzzy_numeric_round_decimals=2,
                enable_fuzzy_fallback=True,
                prefer_fuzzy_if_better=True,
            )
            chosen = comp.fuzzy_best if comp.chosen == "fuzzy" else comp.normal_best
            return chosen.path if chosen is not None else None
        except Exception:
            logger.debug("key-select: no key found")
            return None


def _compute_key_aligned_structure(
    values: Sequence[Any],
    original_paths: Sequence[Optional[str]],
    cascade_cfg: CascadeConfig,
) -> Tuple[Any, Dict[str, List[Optional[str]]]]:
    """One merged aligned structure + mapping from aligned paths to per-source
    original paths."""
    if not values or all(v is None for v in values):
        return None, {}

    non_nulls = [v for v in values if v is not None]
    if not non_nulls:
        return None, {}

    first_type = type(non_nulls[0])
    is_same_type = all(isinstance(v, first_type) for v in non_nulls)
    key_mappings: Dict[str, List[Optional[str]]] = {}

    # Scalars / mixed types: first non-null value represents the column.
    if not is_same_type or first_type not in (dict, list):
        key_mappings[""] = list(original_paths)
        return deepcopy(non_nulls[0]), key_mappings

    if first_type is dict:
        dicts = [v if isinstance(v, dict) else {} for v in values]
        all_keys = sorted(set(key for d in dicts for key in d.keys()))

        aligned_dict: Dict[str, Any] = {}
        for key in all_keys:
            values_for_key = [d.get(key) for d in dicts]
            original_paths_for_key = [
                (f"{p}.{key}" if p else key) if p is not None else None
                for p in original_paths
            ]
            aligned_value, sub_mapping = _compute_key_aligned_structure(
                values_for_key, original_paths_for_key, cascade_cfg
            )
            aligned_dict[key] = aligned_value
            for sub_key, paths in sub_mapping.items():
                key_mappings[f"{key}.{sub_key}" if sub_key else key] = paths
        return aligned_dict, key_mappings

    # first_type is list
    lists = [v if isinstance(v, list) else [] for v in values]
    is_list_of_dicts = all(
        all(isinstance(item, dict) for item in lst) for lst in lists if lst
    )

    if is_list_of_dicts:
        key_paths = _select_key_paths(lists, cascade_cfg)
        if key_paths:
            aligned_rows, original_indices = _align_lists_by_key(lists, key_paths)
            aligned_list = []
            for i, row in enumerate(aligned_rows):
                original_paths_for_row = [
                    (
                        (f"{p}.{original_indices[i][j]}" if p else str(original_indices[i][j]))
                        if (p is not None and original_indices[i][j] is not None)
                        else None
                    )
                    for j, p in enumerate(original_paths)
                ]
                aligned_item, sub_mapping = _compute_key_aligned_structure(
                    row, original_paths_for_row, cascade_cfg
                )
                aligned_list.append(aligned_item)
                for sub_key, paths in sub_mapping.items():
                    key_mappings[f"{i}.{sub_key}" if sub_key else str(i)] = paths
            return aligned_list, key_mappings

    # Zip fallback for scalar lists / failed key selection.
    logger.debug("key-align: zip fallback")
    aligned_list = []
    max_len = max(len(lst) for lst in lists) if lists else 0
    for i in range(max_len):
        row = [lst[i] if i < len(lst) else None for lst in lists]
        original_paths_for_row = [
            ((f"{p}.{i}" if p else str(i)) if i < len(values[j]) else None)
            if p is not None
            else None
            for j, p in enumerate(original_paths)
        ]
        aligned_item, sub_mapping = _compute_key_aligned_structure(
            row, original_paths_for_row, cascade_cfg
        )
        aligned_list.append(aligned_item)
        for sub_key, paths in sub_mapping.items():
            key_mappings[f"{i}.{sub_key}" if sub_key else str(i)] = paths
    return aligned_list, key_mappings


def _get_value_by_path(obj: Any, path: Optional[str]) -> Any:
    """Dot-path lookup with integer list indices; '' is the root."""
    if path is None:
        return None
    if path == "":
        return obj
    cur = obj
    for token in path.split("."):
        if token == "":
            continue
        try:
            idx = int(token)
        except ValueError:
            idx = None
        if idx is not None:
            if isinstance(cur, list) and 0 <= idx < len(cur):
                cur = cur[idx]
                continue
            return None
        if isinstance(cur, dict) and token in cur:
            cur = cur[token]
        else:
            return None
    return cur


def _materialize_source_view(
    aligned_node: Any,
    key_mappings: Dict[str, List[Optional[str]]],
    source_idx: int,
    current_path: str = "",
    source_root: Optional[Dict[str, Any]] = None,
) -> Any:
    """Project the merged structure back into one source's values via the
    path mappings (None where that source contributed nothing)."""
    if source_root is None:
        raise ValueError("source_root must be provided at the top-level call.")

    if isinstance(aligned_node, dict):
        return {
            k: _materialize_source_view(
                v, key_mappings, source_idx, f"{current_path}.{k}" if current_path else k, source_root
            )
            for k, v in aligned_node.items()
        }

    if isinstance(aligned_node, list):
        return [
            _materialize_source_view(
                v, key_mappings, source_idx, f"{current_path}.{i}" if current_path else str(i), source_root
            )
            for i, v in enumerate(aligned_node)
        ]

    mapped_paths = key_mappings.get(current_path)
    if mapped_paths is not None and 0 <= source_idx < len(mapped_paths):
        return _get_value_by_path(source_root, mapped_paths[source_idx])
    return deepcopy(aligned_node)


def recursive_align(
    values: Sequence[Any],
    string_similarity_method: str = "levenshtein",
    min_support_ratio: float = 0.5,
    max_novelty_ratio: float = 0.25,
    current_path: str = "",
    reference_idx: Optional[int] = None,
    min_uniqueness: Optional[float] = None,
    min_coverage: Optional[float] = None,
) -> Tuple[Sequence[Any], Dict[str, List[Optional[str]]]]:
    """Key-based recursive alignment with the similarity aligner's API.

    ``string_similarity_method``/``max_novelty_ratio``/``reference_idx`` are
    accepted for signature parity (the reference ignores them too).
    """
    if not values:
        return list(values), {}
    if all(v is None for v in values):
        return list(values), {current_path: [current_path for _ in values]}

    non_nulls = [v for v in values if v is not None]
    if not non_nulls:
        return list(values), {}

    eff_min_coverage = min_coverage if min_coverage is not None else min_support_ratio
    eff_min_uniqueness = min_uniqueness if min_uniqueness is not None else 0.5
    cascade_cfg = CascadeConfig(
        min_coverage=eff_min_coverage, min_uniqueness=eff_min_uniqueness
    )

    original_paths: List[Optional[str]] = [current_path for _ in values]
    aligned_data, raw_key_mappings = _compute_key_aligned_structure(
        values, original_paths, cascade_cfg
    )

    per_source_outputs: List[Any] = []
    for i, src_root in enumerate(values):
        if isinstance(src_root, dict):
            materialized_root: Dict[str, Any] = src_root
        elif isinstance(src_root, list):
            materialized_root = {"items": src_root}
            # NB: reference parity — the "items." rewrite mutates the shared
            # mapping inside the source loop (:398-400), so list-valued roots
            # with multiple sources double-prefix. The wired swap point only
            # ever passes dict roots, where this path is never taken.
            if raw_key_mappings:
                raw_key_mappings = {
                    (f"items.{k}" if k else "items"): v for k, v in raw_key_mappings.items()
                }
        else:
            materialized_root = {}
        per_source_outputs.append(
            _materialize_source_view(
                aligned_node=aligned_data,
                key_mappings=raw_key_mappings,
                source_idx=i,
                current_path="",
                source_root=materialized_root,
            )
        )

    if current_path:
        prefixed: Dict[str, List[Optional[str]]] = {}
        for key, paths in raw_key_mappings.items():
            pref_key = f"{current_path}.{key}" if key else current_path
            pref_paths: List[Optional[str]] = []
            for p in paths:
                if p is None or p == "":
                    pref_paths.append(current_path if current_path else None)
                else:
                    pref_paths.append(f"{current_path}.{p}" if current_path else p)
            prefixed[pref_key] = pref_paths
        return per_source_outputs, prefixed
    return per_source_outputs, raw_key_mappings
