"""Key-based recursive alignment engine.

Behavioral spec: `/root/reference/k_llms/utils/key_based_alignment.py` —
``_get_key_tuple`` :47-68 (matches on RAW values; only key *selection*
normalizes), ``_align_lists_by_key`` :71-151 (row order from the longest
source, then remaining keys sorted), the recursive merge :156-347 (zip fallback
for scalar lists :324-345), per-source view projection :474-516, and the public
``recursive_align`` :350-431 whose signature matches the similarity aligner so
it can swap in at the documented point (`consolidation.py:22`). Pinned by the
differential oracle in ``tests/test_keyalign.py``.

Design notes: the two row producers (key-tuple alignment and positional zip)
emit a common (row_values, row_positions) plan consumed by one shared merge
loop; source catalogs are first-occurrence dicts rather than parallel
index/set bookkeeping; key selection catches only ``ValueError`` (a missing
key is expected — anything else is a real bug and surfaces).
"""

from __future__ import annotations

import logging
from copy import deepcopy
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .fuzzy import select_best_keys_with_fuzzy_fallback
from .selection import CascadeConfig, _walk, select_best_keys

logger = logging.getLogger(__name__)

PathMap = Dict[str, List[Optional[str]]]
RowPlan = Iterable[Tuple[List[Any], List[Optional[int]]]]


def _get_key_tuple(obj: Dict[str, Any], paths: Tuple[str, ...]) -> Optional[Tuple[Any, ...]]:
    """Raw (un-normalized) key tuple; None if any component is missing, None,
    or a container."""
    parts = [_walk(obj, p) for p in paths]
    if any(v is None or isinstance(v, (dict, list)) for v in parts):
        return None
    return tuple(parts)


def _catalog(source: Any, key_paths: Tuple[str, ...]) -> Dict[Tuple[Any, ...], int]:
    """Key tuple -> first occurrence index for one source list (non-lists and
    non-dict items contribute nothing)."""
    out: Dict[Tuple[Any, ...], int] = {}
    if isinstance(source, list):
        for i, item in enumerate(source):
            if isinstance(item, dict):
                key = _get_key_tuple(item, key_paths)
                if key is not None:
                    out.setdefault(key, i)
    return out


def _align_lists_by_key(
    sources: Sequence[Optional[List[Dict[str, Any]]]], key_paths: Tuple[str, ...]
) -> Tuple[List[List[Optional[Dict[str, Any]]]], List[List[Optional[int]]]]:
    """Rows = key tuples (ordered by the longest source list, then sorted
    leftovers); columns = sources. Returns (aligned_rows, original_indices)."""
    if not any(sources):
        return [], []

    catalogs = [_catalog(src, key_paths) for src in sources]
    anchor = max(
        range(len(sources)),
        key=lambda i: len(sources[i]) if isinstance(sources[i], list) else 0,
    )
    order = list(catalogs[anchor])  # the anchor's first-occurrence order
    order += sorted({k for c in catalogs for k in c} - set(order))

    rows: List[List[Optional[Dict[str, Any]]]] = []
    positions: List[List[Optional[int]]] = []
    for key in order:
        where = [c.get(key) for c in catalogs]
        rows.append([src[i] if i is not None else None for i, src in zip(where, sources)])
        positions.append(where)
    return rows, positions


def _select_key_paths(
    lists: List[List[Any]], cascade_cfg: CascadeConfig
) -> Optional[Tuple[str, ...]]:
    """Standard selection (composite-aware) first; fuzzy preferred when it
    improves stability; fuzzy-only as last resort."""
    wrapped = [{"items": lst} for lst in lists]

    def fuzzy_comparison():
        return select_best_keys_with_fuzzy_fallback(
            wrapped,
            cascade_cfg=cascade_cfg,
            list_key="items",
            fuzzy_numeric_round_decimals=2,
            enable_fuzzy_fallback=True,
            prefer_fuzzy_if_better=True,
        )

    try:
        picked = select_best_keys(wrapped, list_key="items", cascade_cfg=cascade_cfg)
    except ValueError:
        # No exact key at all — fuzzy canonicalization is the last resort.
        try:
            comparison = fuzzy_comparison()
        except ValueError:
            logger.debug("key-select: no key found")
            return None
        winner = (
            comparison.fuzzy_best if comparison.chosen == "fuzzy" else comparison.normal_best
        )
        return winner.path if winner is not None else None

    exact = picked.best_single
    if (
        picked.best_composite is not None
        and picked.best_composite.score_tuple > exact.score_tuple
    ):
        exact = picked.best_composite
    try:
        comparison = fuzzy_comparison()
        if comparison.chosen == "fuzzy" and comparison.fuzzy_best is not None:
            logger.debug("key-select: fuzzy path %s", comparison.fuzzy_best.path)
            return comparison.fuzzy_best.path
    except ValueError:
        pass
    logger.debug("key-select: standard path %s", exact.path)
    return exact.path


def _merge_rows(
    plan: RowPlan, origins: Sequence[Optional[str]], cascade_cfg: CascadeConfig
) -> Tuple[List[Any], PathMap]:
    """Merge each planned row and collect its mapping under the row index."""
    merged: List[Any] = []
    mapping: PathMap = {}
    for i, (row, where) in enumerate(plan):
        row_origins = [
            None if (p is None or q is None) else (f"{p}.{q}" if p else str(q))
            for p, q in zip(origins, where)
        ]
        item, sub = _merge_column(row, row_origins, cascade_cfg)
        merged.append(item)
        for leaf, srcs in sub.items():
            mapping[f"{i}.{leaf}" if leaf else str(i)] = srcs
    return merged, mapping


def _merge_column(
    values: Sequence[Any],
    origins: Sequence[Optional[str]],
    cascade_cfg: CascadeConfig,
) -> Tuple[Any, PathMap]:
    """One merged aligned structure + mapping from aligned paths to per-source
    original paths."""
    present = [v for v in values if v is not None]
    if not present:
        return None, {}
    head = type(present[0])

    # Scalars / mixed types: first non-null value represents the column, and
    # every source keeps its inherited path (contributing or not).
    if head not in (dict, list) or not all(isinstance(v, head) for v in present):
        return deepcopy(present[0]), {"": list(origins)}

    if head is dict:
        shells = [v if isinstance(v, dict) else {} for v in values]
        merged: Dict[str, Any] = {}
        mapping: PathMap = {}
        for key in sorted({k for d in shells for k in d}):
            child_origins = [
                None if p is None else (f"{p}.{key}" if p else key) for p in origins
            ]
            merged[key], sub = _merge_column(
                [d.get(key) for d in shells], child_origins, cascade_cfg
            )
            for leaf, srcs in sub.items():
                mapping[f"{key}.{leaf}" if leaf else key] = srcs
        return merged, mapping

    rows = [v if isinstance(v, list) else [] for v in values]
    uniform_dicts = all(isinstance(item, dict) for lst in rows if lst for item in lst)
    if uniform_dicts:
        key_paths = _select_key_paths(rows, cascade_cfg)
        if key_paths:
            aligned, positions = _align_lists_by_key(rows, key_paths)
            return _merge_rows(zip(aligned, positions), origins, cascade_cfg)

    # Positional zip for scalar lists / failed key selection. NB the position
    # gate reads len(values[j]) — the raw value, not the list-coerced one —
    # faithfully to the spec (:332).
    logger.debug("key-align: zip fallback")
    width = max((len(lst) for lst in rows), default=0)
    plan = (
        (
            [lst[i] if i < len(lst) else None for lst in rows],
            [
                # len(values[j]) must stay unevaluated for non-contributing
                # sources (the spec only touches it under `p is not None`).
                None
                if origins[j] is None
                else (i if i < len(values[j]) else None)
                for j in range(len(values))
            ],
        )
        for i in range(width)
    )
    return _merge_rows(plan, origins, cascade_cfg)


def _lookup(root: Any, path: Optional[str]) -> Any:
    """Dot-path lookup with integer list indices; '' is the root."""
    if path is None:
        return None
    node = root
    for token in path.split("."):
        if token == "":
            continue
        try:
            i = int(token)
        except ValueError:
            i = None
        if i is not None:
            # Numeric tokens only ever index lists; a dict with a numeric
            # string key is unreachable through them.
            if not (isinstance(node, list) and 0 <= i < len(node)):
                return None
            node = node[i]
        elif isinstance(node, dict) and token in node:
            node = node[token]
        else:
            return None
    return node


def _project(
    aligned_node: Any,
    key_mappings: PathMap,
    source_idx: int,
    current_path: str,
    source_root: Any,
) -> Any:
    """Project the merged structure back into one source's values via the
    path mappings (None where that source contributed nothing)."""
    if isinstance(aligned_node, dict):
        items = aligned_node.items()
    elif isinstance(aligned_node, list):
        items = enumerate(aligned_node)
    else:
        routed = key_mappings.get(current_path)
        if routed is not None and 0 <= source_idx < len(routed):
            return _lookup(source_root, routed[source_idx])
        return deepcopy(aligned_node)

    def child(token):
        return f"{current_path}.{token}" if current_path else str(token)

    projected = (
        (k, _project(v, key_mappings, source_idx, child(k), source_root)) for k, v in items
    )
    if isinstance(aligned_node, dict):
        return dict(projected)
    return [v for _, v in projected]


def recursive_align(
    values: Sequence[Any],
    string_similarity_method: str = "levenshtein",
    min_support_ratio: float = 0.5,
    max_novelty_ratio: float = 0.25,
    current_path: str = "",
    reference_idx: Optional[int] = None,
    min_uniqueness: Optional[float] = None,
    min_coverage: Optional[float] = None,
) -> Tuple[Sequence[Any], PathMap]:
    """Key-based recursive alignment with the similarity aligner's API.

    ``string_similarity_method``/``max_novelty_ratio``/``reference_idx`` are
    accepted for signature parity (the reference ignores them too).
    """
    if not values:
        return list(values), {}
    if all(v is None for v in values):
        return list(values), {current_path: [current_path] * len(values)}

    cascade_cfg = CascadeConfig(
        min_coverage=min_support_ratio if min_coverage is None else min_coverage,
        min_uniqueness=0.5 if min_uniqueness is None else min_uniqueness,
    )

    merged, mapping = _merge_column(values, [current_path] * len(values), cascade_cfg)

    views: List[Any] = []
    for idx, root in enumerate(values):
        if isinstance(root, dict):
            wrapped: Any = root
        elif isinstance(root, list):
            wrapped = {"items": root}
            # NB spec parity: the "items." rewrite mutates the shared mapping
            # inside the source loop (:398-400), so list-valued roots with
            # multiple sources double-prefix. The wired swap point only ever
            # passes dict roots, where this path is never taken.
            if mapping:
                mapping = {(f"items.{k}" if k else "items"): v for k, v in mapping.items()}
        else:
            wrapped = {}
        views.append(
            _project(merged, mapping, idx, current_path="", source_root=wrapped)
        )

    if not current_path:
        return views, mapping
    rebased: PathMap = {}
    for key, paths in mapping.items():
        rebased[f"{current_path}.{key}" if key else current_path] = [
            current_path if not p else f"{current_path}.{p}" for p in paths
        ]
    return views, rebased
