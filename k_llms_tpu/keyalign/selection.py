"""Join-key discovery and cascade selection.

Parity target: `/root/reference/k_llms/utils/key_selection.py` — path discovery
:100-121, metrics :154-214 (coverage / uniqueness / pairwise-Jaccard stability /
support histogram with the 9-component lexicographic score), the 4-stage cascade
funnel :310-367, and greedy + brute-force composite search :412-437.

One cascade implementation serves both the standard and fuzzy selectors via a
``canonicalize`` hook (the reference duplicates the funnel).
"""

from __future__ import annotations

import math
import re
from collections import Counter
from itertools import combinations
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from pydantic import BaseModel, ConfigDict

JSONPath = str

# Configurable record-list keys checked before auto-detection.
RECORD_LIST_KEYS: List[str] = ["products"]

_WS = re.compile(r"\s+")


def normalize_scalar(value: Any) -> Any:
    """Lowercase + collapse whitespace for strings; other scalars pass through."""
    if isinstance(value, str):
        return _WS.sub(" ", value.strip().lower())
    return value


def iter_records(
    extraction: Dict[str, Any], list_key: Optional[str] = None
) -> List[Dict[str, Any]]:
    """Record dicts from ``list_key``, else RECORD_LIST_KEYS, else every
    list-of-dicts value in order."""
    records: List[Dict[str, Any]] = []
    if list_key is not None:
        seq = extraction.get(list_key)
        if isinstance(seq, list):
            records.extend(item for item in seq if isinstance(item, dict))
        return records

    for candidate_key in RECORD_LIST_KEYS:
        seq = extraction.get(candidate_key)
        if isinstance(seq, list):
            records.extend(item for item in seq if isinstance(item, dict))
    if records:
        return records

    for value in extraction.values():
        if isinstance(value, list):
            records.extend(item for item in value if isinstance(item, dict))
    return records


def _resolve_path(record: Any, parts: List[str]) -> Tuple[bool, Any]:
    cur = record
    for token in parts:
        if isinstance(cur, dict) and token in cur:
            cur = cur[token]
        else:
            return False, None
    return True, cur


def values_for_path(
    extraction: Dict[str, Any],
    path: JSONPath,
    list_key: Optional[str] = None,
    canonicalize: Callable[[Any], Any] = normalize_scalar,
) -> List[Any]:
    """Scalar values at a dot path across all records of one extraction."""
    parts = path.split(".")
    out: List[Any] = []
    for record in iter_records(extraction, list_key=list_key):
        if not isinstance(record, dict):
            continue
        ok, cur = _resolve_path(record, parts)
        if ok and cur is not None and not isinstance(cur, (dict, list)):
            out.append(canonicalize(cur))
    return out


def tuple_values_for_paths(
    extraction: Dict[str, Any],
    paths: List[JSONPath],
    list_key: Optional[str] = None,
    canonicalize: Callable[[Any], Any] = normalize_scalar,
) -> List[Tuple[Any, ...]]:
    """Composite-key tuples across records; records missing any component drop out."""
    parts_list = [p.split(".") for p in paths]
    out: List[Tuple[Any, ...]] = []
    for record in iter_records(extraction, list_key=list_key):
        if not isinstance(record, dict):
            continue
        components: List[Any] = []
        for parts in parts_list:
            ok, cur = _resolve_path(record, parts)
            if not ok or cur is None or isinstance(cur, (dict, list)):
                components = []
                break
            components.append(canonicalize(cur))
        if components:
            out.append(tuple(components))
    return out


def discover_scalar_paths(
    extractions: List[Dict[str, Any]], list_key: Optional[str] = None
) -> List[JSONPath]:
    """Dot paths resolving to scalars anywhere in any record (lists excluded)."""
    candidates: Set[str] = set()
    for extraction in extractions:
        for record in iter_records(extraction, list_key=list_key):
            if not isinstance(record, dict):
                continue
            stack: List[Tuple[str, Any]] = [("", record)]
            while stack:
                base, node = stack.pop()
                if not isinstance(node, dict):
                    continue
                for key, value in node.items():
                    path = f"{base}.{key}" if base else key
                    if isinstance(value, dict):
                        stack.append((path, value))
                    elif isinstance(value, list):
                        continue
                    else:
                        candidates.add(path)
    return sorted(candidates)


def jaccard(a: Set[Any], b: Set[Any]) -> float:
    if not a and not b:
        return 1.0
    if not a or not b:
        return 0.0
    uni = len(a | b)
    return len(a & b) / uni if uni else 1.0


class KeyMetrics(BaseModel):
    model_config = ConfigDict(frozen=True)

    path: Tuple[str, ...]  # 1 path for single keys, >1 for composite
    coverage_min: float
    coverage_mean: float
    uniqueness_min: float
    uniqueness_mean: float
    jaccard_min: float
    jaccard_mean: float
    I_E: int  # values present in all extractions
    I_E_minus_1: int  # present in E-1 extractions
    I_ge_2: int  # present in at least 2 extractions
    union_size: int
    score_tuple: Tuple  # lexicographic ranking score


def _evaluate(
    extractions: List[Dict[str, Any]],
    per_vals: List[List[Any]],
    path: Tuple[str, ...],
    depth_hint: int,
    n_paths: int,
    list_key: Optional[str],
) -> KeyMetrics:
    E = len(extractions)
    per_sets = [set(vs) for vs in per_vals]

    coverage: List[float] = []
    uniqueness: List[float] = []
    for vs, e in zip(per_vals, extractions):
        total = len(iter_records(e, list_key=list_key))
        non_null = len(vs)
        coverage.append(non_null / max(1, total))
        cnt = Counter(vs)
        uniq = sum(1 for _v, c in cnt.items() if c == 1)
        uniqueness.append(uniq / max(1, non_null) if non_null else 0.0)

    j_scores = [
        jaccard(per_sets[i], per_sets[j]) for i in range(E) for j in range(i + 1, E)
    ]
    j_mean = sum(j_scores) / len(j_scores) if j_scores else 1.0
    j_min = min(j_scores) if j_scores else 1.0

    support: Counter = Counter()
    for s in per_sets:
        for v in s:
            support[v] += 1
    counts_by_sup = Counter(support.values())
    I_E = counts_by_sup.get(E, 0)
    I_Em1 = counts_by_sup.get(E - 1, 0) if E >= 2 else 0
    I_2p = sum(c for sup, c in counts_by_sup.items() if sup >= 2)
    U = len(set().union(*per_sets)) if per_sets else 0

    score_tuple = (
        round(j_min, 6),  # 1) worst-pair Jaccard
        I_E,  # 2) values present in all files
        I_Em1,  # 3) then E-1 files
        round(j_mean, 6),  # 4) mean Jaccard
        round(min(uniqueness), 6),  # 5) intra-JSON uniqueness (min)
        round(min(coverage), 6),  # 6) intra-JSON coverage (min)
        -U,  # 7) discourage large unions
        depth_hint,  # 8) prefer deeper paths
        -n_paths,  # 9) prefer fewer key components
    )

    return KeyMetrics(
        path=path,
        coverage_min=min(coverage) if coverage else 0.0,
        coverage_mean=sum(coverage) / len(coverage) if coverage else 0.0,
        uniqueness_min=min(uniqueness) if uniqueness else 0.0,
        uniqueness_mean=sum(uniqueness) / len(uniqueness) if uniqueness else 0.0,
        jaccard_min=j_min,
        jaccard_mean=j_mean,
        I_E=I_E,
        I_E_minus_1=I_Em1,
        I_ge_2=I_2p,
        union_size=U,
        score_tuple=score_tuple,
    )


def evaluate_single_key(
    extractions: List[Dict[str, Any]],
    path: JSONPath,
    list_key: Optional[str] = None,
    canonicalize: Callable[[Any], Any] = normalize_scalar,
) -> KeyMetrics:
    per_vals = [
        values_for_path(e, path, list_key=list_key, canonicalize=canonicalize)
        for e in extractions
    ]
    return _evaluate(
        extractions, per_vals, (path,), depth_hint=path.count("."), n_paths=1, list_key=list_key
    )


def evaluate_composite_key(
    extractions: List[Dict[str, Any]],
    paths: List[JSONPath],
    list_key: Optional[str] = None,
    canonicalize: Callable[[Any], Any] = normalize_scalar,
) -> KeyMetrics:
    per_vals = [
        tuple_values_for_paths(e, paths, list_key=list_key, canonicalize=canonicalize)
        for e in extractions
    ]
    return _evaluate(
        extractions,
        per_vals,
        tuple(paths),
        depth_hint=sum(p.count(".") for p in paths),
        n_paths=len(paths),
        list_key=list_key,
    )


class CascadeConfig(BaseModel):
    model_config = ConfigDict(frozen=True)

    min_coverage: float = 0.0
    min_uniqueness: float = 0.0
    topk_stage1: int = 30  # after stability sort
    topk_stage2: int = 12  # after intra-JSON sort
    topk_stage3: int = 6  # after union filter


class CascadeReport(BaseModel):
    model_config = ConfigDict(frozen=True)

    stage0_kept: List[KeyMetrics]
    stage1_kept: List[KeyMetrics]
    stage2_kept: List[KeyMetrics]
    stage3_kept: List[KeyMetrics]
    final_best: KeyMetrics


def cascade_select_keys(
    extractions: List[Dict[str, Any]],
    candidates: List[str],
    config: CascadeConfig = CascadeConfig(),
    list_key: Optional[str] = None,
    canonicalize: Callable[[Any], Any] = normalize_scalar,
) -> CascadeReport:
    """4-stage funnel: gate -> stability -> intra-JSON quality -> parsimony,
    with depth/fewer-components tie-breakers."""
    singles = [
        evaluate_single_key(extractions, p, list_key=list_key, canonicalize=canonicalize)
        for p in candidates
    ]

    pool0 = [
        m
        for m in singles
        if (
            m.I_ge_2 > 0
            and m.jaccard_min > 0.0
            and m.coverage_min >= config.min_coverage
            and m.uniqueness_min >= config.min_uniqueness
        )
    ]
    if not pool0:
        raise ValueError(
            "No keys pass Stage 0 (require I_ge_2>0, jaccard_min>0, and coverage)."
        )

    pool1 = sorted(
        pool0,
        key=lambda m: (m.I_E, m.I_E_minus_1, round(m.jaccard_min, 6), round(m.jaccard_mean, 6)),
        reverse=True,
    )[: config.topk_stage1]

    pool2 = sorted(
        pool1,
        key=lambda m: (round(m.uniqueness_min, 6), round(m.coverage_min, 6)),
        reverse=True,
    )[: config.topk_stage2]

    pool3 = sorted(pool2, key=lambda m: (m.union_size,))[: config.topk_stage3]

    final_sorted = sorted(
        pool3,
        key=lambda m: (sum(p.count(".") for p in m.path), -len(m.path)),
        reverse=True,
    )

    return CascadeReport(
        stage0_kept=pool0,
        stage1_kept=pool1,
        stage2_kept=pool2,
        stage3_kept=pool3,
        final_best=final_sorted[0],
    )


class KeySelectionResult(BaseModel):
    model_config = ConfigDict(frozen=True)

    best_single: KeyMetrics
    best_composite: Optional[KeyMetrics]
    candidate_table: List[KeyMetrics]
    min_support_for_autolock: int
    cascade_report: CascadeReport


def stability_tuple(m: KeyMetrics) -> Tuple:
    return (round(m.jaccard_min, 6), m.I_E, m.I_E_minus_1, round(m.jaccard_mean, 6))


def select_best_keys(
    extractions: List[Dict[str, Any]],
    max_candidates_for_composite: int = 20,
    max_k: int = 3,
    min_support_ratio_for_autolock: float = 0.75,
    cascade_cfg: CascadeConfig = CascadeConfig(),
    list_key: Optional[str] = None,
) -> KeySelectionResult:
    """Cascade over singles, then greedy + brute-force composite improvement."""
    if not extractions:
        raise ValueError("No extractions provided.")

    E = len(extractions)
    t = max(2, math.ceil(min_support_ratio_for_autolock * E))

    candidates = discover_scalar_paths(extractions, list_key=list_key)
    if not candidates:
        raise ValueError("No scalar candidate paths discovered.")

    report = cascade_select_keys(extractions, candidates, cascade_cfg, list_key=list_key)
    best_single = report.final_best

    singles_all = [
        evaluate_single_key(extractions, p, list_key=list_key) for p in candidates
    ]
    singles_all = [m for m in singles_all if (m.I_ge_2 > 0 and m.jaccard_min > 0.0)]
    singles_all.sort(
        key=lambda m: (
            round(m.jaccard_min, 6),
            m.I_E,
            m.I_E_minus_1,
            round(m.jaccard_mean, 6),
            round(m.uniqueness_min, 6),
            round(m.coverage_min, 6),
            -m.union_size,
        ),
        reverse=True,
    )

    # Greedy growth from the stage-3 pool (strict improvement on BOTH score and
    # stability), then a brute-force sweep over 2..max_k combinations accepting
    # either-improves — matching the reference's accept conditions (:426, :436).
    topN_paths = [m.path[0] for m in report.stage3_kept][:max_candidates_for_composite]
    best_combo: Optional[KeyMetrics] = None
    if topN_paths:
        current = [topN_paths[0]]
        best_combo = evaluate_composite_key(extractions, current, list_key=list_key)
        improved = True
        while improved and len(current) < max_k:
            improved = False
            for cand in (p for p in topN_paths if p not in current):
                trial = evaluate_composite_key(extractions, current + [cand], list_key=list_key)
                if trial.score_tuple > best_combo.score_tuple and stability_tuple(
                    trial
                ) > stability_tuple(best_combo):
                    best_combo = trial
                    current.append(cand)
                    improved = True

        for r in range(2, min(max_k, len(topN_paths)) + 1):
            for combo in combinations(topN_paths, r):
                trial = evaluate_composite_key(extractions, list(combo), list_key=list_key)
                if stability_tuple(trial) > stability_tuple(best_combo) or (
                    trial.score_tuple > best_combo.score_tuple
                ):
                    best_combo = trial

    return KeySelectionResult(
        best_single=best_single,
        best_composite=best_combo,
        candidate_table=singles_all,
        min_support_for_autolock=t,
        cascade_report=report,
    )
