"""Join-key discovery and cascade selection.

Behavioral spec: `/root/reference/k_llms/utils/key_selection.py` — path
discovery :100-121, metrics :154-214 (coverage / uniqueness / pairwise-Jaccard
stability / support histogram feeding a 9-component lexicographic score), the
4-stage cascade funnel :310-367, and greedy + brute-force composite search
:412-437 — pinned by the differential oracle in ``tests/test_keyalign.py``.

Design differences from the reference: single and composite keys share ONE
tuple-valued projection (a single key is a 1-tuple — the score depends on
values only through equality, so the wrapping is invisible); metrics are a
frozen dataclass whose ranking tuples are derived properties; and the funnel is
data-driven (a list of (rank, cap) stages folded over the candidate pool). One
cascade serves both the standard and fuzzy selectors via a ``canonicalize``
hook (the reference duplicates the funnel).
"""

from __future__ import annotations

import math
import re
from collections import Counter
from dataclasses import dataclass
from itertools import combinations
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

JSONPath = str

# Record-container keys probed before falling back to auto-detection.
RECORD_LIST_KEYS: List[str] = ["products"]

_SQUEEZE = re.compile(r"\s+")


def normalize_scalar(value: Any) -> Any:
    """Lowercase + collapse whitespace for strings; other scalars pass through."""
    if not isinstance(value, str):
        return value
    return _SQUEEZE.sub(" ", value.strip().lower())


def iter_records(
    extraction: Dict[str, Any], list_key: Optional[str] = None
) -> List[Dict[str, Any]]:
    """Record dicts from ``list_key``, else RECORD_LIST_KEYS, else every
    list-of-dicts value in order."""

    def dicts_in(container: Any) -> Iterator[Dict[str, Any]]:
        if isinstance(container, list):
            yield from (x for x in container if isinstance(x, dict))

    if list_key is not None:
        return list(dicts_in(extraction.get(list_key)))
    named = [r for k in RECORD_LIST_KEYS for r in dicts_in(extraction.get(k))]
    if named:
        return named
    return [r for v in extraction.values() for r in dicts_in(v)]


def _walk(record: Any, dotted: str) -> Any:
    """Resolve a dot path inside nested dicts; a sentinel miss returns None
    (scalar None and a miss are treated the same by every caller)."""
    node = record
    for step in dotted.split("."):
        if not (isinstance(node, dict) and step in node):
            return None
        node = node[step]
    return node


def project_key(
    extraction: Dict[str, Any],
    key: Tuple[JSONPath, ...],
    list_key: Optional[str] = None,
    canonicalize: Callable[[Any], Any] = normalize_scalar,
) -> List[Tuple[Any, ...]]:
    """Canonicalized key tuples across one extraction's records. A record drops
    out when any component is missing, None, or a container."""
    rows: List[Tuple[Any, ...]] = []
    for record in iter_records(extraction, list_key=list_key):
        parts = [_walk(record, p) for p in key]
        if any(v is None or isinstance(v, (dict, list)) for v in parts):
            continue
        rows.append(tuple(canonicalize(v) for v in parts))
    return rows


def discover_scalar_paths(
    extractions: List[Dict[str, Any]], list_key: Optional[str] = None
) -> List[JSONPath]:
    """Dot paths resolving to scalars anywhere in any record (lists excluded)."""

    def scalar_paths(node: Dict[str, Any], base: str) -> Iterator[str]:
        for k, v in node.items():
            dotted = f"{base}.{k}" if base else k
            if isinstance(v, dict):
                yield from scalar_paths(v, dotted)
            elif not isinstance(v, list):
                yield dotted

    found = {
        p
        for e in extractions
        for rec in iter_records(e, list_key=list_key)
        for p in scalar_paths(rec, "")
    }
    return sorted(found)


def jaccard(a: set, b: set) -> float:
    if not (a or b):
        return 1.0
    union = a | b
    return len(a & b) / len(union) if union else 1.0


@dataclass(frozen=True)
class KeyMetrics:
    """Quality profile of one candidate key across the extraction family.

    ``overlap_*`` = pairwise Jaccard of value sets; ``n_all`` / ``n_all_but_1``
    / ``n_shared`` = support histogram (values seen in every / all-but-one /
    >=2 extractions); ``cover_*`` / ``unique_*`` = per-extraction record
    coverage and value uniqueness, min/mean-aggregated."""

    path: Tuple[str, ...]
    cover_lo: float
    cover_avg: float
    unique_lo: float
    unique_avg: float
    overlap_lo: float
    overlap_avg: float
    n_all: int
    n_all_but_1: int
    n_shared: int
    union_n: int

    @property
    def depth(self) -> int:
        return sum(p.count(".") for p in self.path)

    @property
    def score_tuple(self) -> Tuple:
        """9-component lexicographic rank: worst-pair overlap, full/near-full
        support, mean overlap, uniqueness, coverage, small unions, deep paths,
        few components."""
        return (
            round(self.overlap_lo, 6),
            self.n_all,
            self.n_all_but_1,
            round(self.overlap_avg, 6),
            round(self.unique_lo, 6),
            round(self.cover_lo, 6),
            -self.union_n,
            self.depth,
            -len(self.path),
        )

    @property
    def stability(self) -> Tuple:
        return (round(self.overlap_lo, 6), self.n_all, self.n_all_but_1, round(self.overlap_avg, 6))


def measure_key(
    extractions: List[Dict[str, Any]],
    key: Tuple[JSONPath, ...],
    list_key: Optional[str] = None,
    canonicalize: Callable[[Any], Any] = normalize_scalar,
) -> KeyMetrics:
    """Profile one candidate key (any arity) across the extraction family."""
    columns = [
        project_key(e, key, list_key=list_key, canonicalize=canonicalize) for e in extractions
    ]
    value_sets = [set(c) for c in columns]
    n_files = len(extractions)

    cover: List[float] = []
    unique: List[float] = []
    for rows, e in zip(columns, extractions):
        n_records = len(iter_records(e, list_key=list_key))
        cover.append(len(rows) / max(1, n_records))
        if rows:
            tally = Counter(rows)
            unique.append(sum(1 for n in tally.values() if n == 1) / max(1, len(rows)))
        else:
            unique.append(0.0)

    overlaps = [jaccard(a, b) for a, b in combinations(value_sets, 2)]
    seen_in = Counter(v for s in value_sets for v in s)
    histogram = Counter(seen_in.values())

    return KeyMetrics(
        path=key,
        cover_lo=min(cover, default=0.0),
        cover_avg=sum(cover) / len(cover) if cover else 0.0,
        unique_lo=min(unique, default=0.0),
        unique_avg=sum(unique) / len(unique) if unique else 0.0,
        overlap_lo=min(overlaps, default=1.0),
        overlap_avg=sum(overlaps) / len(overlaps) if overlaps else 1.0,
        n_all=histogram.get(n_files, 0),
        n_all_but_1=histogram.get(n_files - 1, 0) if n_files >= 2 else 0,
        n_shared=sum(n for support, n in histogram.items() if support >= 2),
        union_n=len(seen_in),
    )


@dataclass(frozen=True)
class CascadeConfig:
    min_coverage: float = 0.0
    min_uniqueness: float = 0.0
    topk_stage1: int = 30  # survivors of the stability sort
    topk_stage2: int = 12  # survivors of the intra-JSON sort
    topk_stage3: int = 6  # survivors of the union-parsimony sort


@dataclass(frozen=True)
class CascadeReport:
    stage0_kept: List[KeyMetrics]
    stage1_kept: List[KeyMetrics]
    stage2_kept: List[KeyMetrics]
    stage3_kept: List[KeyMetrics]
    final_best: KeyMetrics


def cascade_select_keys(
    extractions: List[Dict[str, Any]],
    candidates: List[str],
    config: CascadeConfig = CascadeConfig(),
    list_key: Optional[str] = None,
    canonicalize: Callable[[Any], Any] = normalize_scalar,
) -> CascadeReport:
    """4-stage funnel: admission gate -> stability -> intra-JSON quality ->
    union parsimony, finished by a depth / fewer-components tie-break."""
    admitted = [
        m
        for m in (
            measure_key(extractions, (p,), list_key=list_key, canonicalize=canonicalize)
            for p in candidates
        )
        if m.n_shared > 0
        and m.overlap_lo > 0.0
        and m.cover_lo >= config.min_coverage
        and m.unique_lo >= config.min_uniqueness
    ]
    if not admitted:
        raise ValueError(
            "No keys pass Stage 0 (require shared values, nonzero worst-pair "
            "overlap, and the coverage/uniqueness gates)."
        )

    funnel = (
        (
            lambda m: (m.n_all, m.n_all_but_1, round(m.overlap_lo, 6), round(m.overlap_avg, 6)),
            True,
            config.topk_stage1,
        ),
        (lambda m: (round(m.unique_lo, 6), round(m.cover_lo, 6)), True, config.topk_stage2),
        (lambda m: m.union_n, False, config.topk_stage3),
    )
    pools = [admitted]
    for rank, descending, cap in funnel:
        pools.append(sorted(pools[-1], key=rank, reverse=descending)[:cap])

    winner = max(pools[-1], key=lambda m: (m.depth, -len(m.path)))
    return CascadeReport(*pools, final_best=winner)


@dataclass(frozen=True)
class KeySelectionResult:
    best_single: KeyMetrics
    best_composite: Optional[KeyMetrics]
    candidate_table: List[KeyMetrics]
    min_support_for_autolock: int
    cascade_report: CascadeReport


def stability_tuple(m: KeyMetrics) -> Tuple:
    return m.stability


def select_best_keys(
    extractions: List[Dict[str, Any]],
    max_candidates_for_composite: int = 20,
    max_k: int = 3,
    min_support_ratio_for_autolock: float = 0.75,
    cascade_cfg: CascadeConfig = CascadeConfig(),
    list_key: Optional[str] = None,
) -> KeySelectionResult:
    """Cascade over singles, then greedy + brute-force composite improvement."""
    if not extractions:
        raise ValueError("No extractions provided.")
    candidates = discover_scalar_paths(extractions, list_key=list_key)
    if not candidates:
        raise ValueError("No scalar candidate paths discovered.")

    report = cascade_select_keys(extractions, candidates, cascade_cfg, list_key=list_key)

    # Ranked table of every admissible single key (diagnostic output).
    table = sorted(
        (
            m
            for m in (measure_key(extractions, (p,), list_key=list_key) for p in candidates)
            if m.n_shared > 0 and m.overlap_lo > 0.0
        ),
        key=lambda m: m.score_tuple[:7],
        reverse=True,
    )

    # Composite search seeded from the stage-3 pool: greedy growth requires a
    # strict improvement on BOTH score and stability; the brute-force sweep over
    # 2..max_k combinations accepts either-improves (reference :426, :436).
    seeds = [m.path[0] for m in report.stage3_kept][:max_candidates_for_composite]
    champion: Optional[KeyMetrics] = None
    if seeds:
        chosen = [seeds[0]]
        champion = measure_key(extractions, tuple(chosen), list_key=list_key)
        growing = True
        while growing and len(chosen) < max_k:
            growing = False
            for extra in seeds:
                if extra in chosen:
                    continue
                trial = measure_key(extractions, tuple(chosen + [extra]), list_key=list_key)
                if trial.score_tuple > champion.score_tuple and trial.stability > champion.stability:
                    champion, chosen, growing = trial, chosen + [extra], True

        for arity in range(2, min(max_k, len(seeds)) + 1):
            for combo in combinations(seeds, arity):
                trial = measure_key(extractions, combo, list_key=list_key)
                if trial.stability > champion.stability or trial.score_tuple > champion.score_tuple:
                    champion = trial

    return KeySelectionResult(
        best_single=report.final_best,
        best_composite=champion,
        candidate_table=table,
        min_support_for_autolock=max(2, math.ceil(min_support_ratio_for_autolock * len(extractions))),
        cascade_report=report,
    )
