"""Key-based latent aligner.

Deterministic alternative to the similarity aligner: lists of JSON records are
aligned by the best-scoring scalar "join key" (single or composite) instead of
pairwise similarity. Parity targets: `/root/reference/k_llms/utils/
key_selection.py`, `fuzzy_key_selection.py`, `key_based_alignment.py`. The
public ``recursive_align`` keeps the documented swap-point signature
(`/root/reference/k_llms/utils/consolidation.py:22`).

Structural difference vs the reference: the standard and fuzzy cascades are ONE
parametrized funnel (the reference duplicates ~60 lines); behavior is
differential-tested identical.
"""

from .selection import (
    CascadeConfig,
    KeyMetrics,
    KeySelectionResult,
    discover_scalar_paths,
    iter_records,
    select_best_keys,
)
from .fuzzy import SelectionComparison, select_best_keys_with_fuzzy_fallback
from .align import recursive_align

__all__ = [
    "CascadeConfig",
    "KeyMetrics",
    "KeySelectionResult",
    "SelectionComparison",
    "discover_scalar_paths",
    "iter_records",
    "select_best_keys",
    "select_best_keys_with_fuzzy_fallback",
    "recursive_align",
]
