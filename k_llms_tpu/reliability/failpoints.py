"""Deterministic failpoint registry (fail-rs / Jepsen-style fault injection).

Every hardened failure path in the serving stack must be exercisable on CPU
without real faults. Sites are named strings compiled into the hot path as a
single dict lookup against an almost-always-empty registry (no-op in
production); activation is per-test via the ``failpoints`` context manager or
process-wide via ``KLLMS_FAILPOINTS``.

Injection sites wired in this package:

- ``scheduler.admit``    — evaluated at submit time (admission control)
- ``engine.launch``      — evaluated at the top of every coalesced batch
                           launch, inside the OOM guard; the ``oom`` action
                           here exercises split-and-requeue without a device
- ``engine.decode``      — evaluated per request around the decode loop;
                           ``kill_samples`` marks a seeded subset of the n
                           samples as lost mid-decode
- ``engine.logits``      — evaluated once per launch before the decode loop;
                           the ``nan`` action poisons a seeded subset of the
                           batch rows' first-step logits, exercising the
                           numeric-integrity quarantine
- ``loader.params``      — evaluated inside ``load_checkpoint``; ``corrupt``
                           flips bytes in a loaded float leaf so integrity
                           verification must fail fast
- ``backend.dispatch``   — evaluated per dispatch attempt (retry/circuit path)
- ``consensus.consolidate`` — evaluated at consolidation entry
- ``replica.dispatch``   — evaluated (keyed by replica id) before every member
                           dispatch of a :class:`ReplicaSet` — primary,
                           failover, and hedge attempts alike; the ``down``
                           action kills the attempt with a replica-health
                           error so routing must fail over
- ``replica.probe``      — evaluated (keyed by replica id) at the top of a
                           replica health probe; ``fail`` keeps a pulled
                           member out of rotation until the spec exhausts
- ``engine.pages``       — evaluated when the continuous decode loop releases
                           a retired slot's KV pages; the ``leak`` action
                           drops ``kill`` pages from the pool's free stack
                           without accounting, so the page-conservation
                           invariant (``ContinuousDecodeLoop.stats``) must
                           fail fast instead of serving from a corrupt pool
- ``serving.request``    — evaluated by the HTTP front door at request entry
                           (``serving/app.py``); the ``disconnect`` action
                           makes the server treat the client as having dropped
                           mid-stream after the first delta chunk, exercising
                           the disconnect → budget-cancel → decode-abort path
                           without a real socket teardown
- ``consensus.device``   — evaluated at the top of the device-consensus
                           prepare step (``consensus/device.py``); the
                           ``fallback`` action forces the scorer to degrade to
                           the host similarity/voting path for that
                           consolidation, exercising the automatic-fallback
                           contract (zero request failures) mid-traffic
- ``ops.paged_attn``     — evaluated when a decode loop/launch resolves its
                           paged-attention implementation
                           (``ops/paged_attention.py``); the ``fallback``
                           action forces the counted degrade from the fused
                           Pallas kernel to the XLA reference (recording
                           ``kernel.paged_attn_fallback.failpoint``),
                           exercising the kernel-unavailable path without
                           leaving the TPU build
- ``engine.grammar``     — evaluated when ``grammar_for_schema`` resolves a
                           compiled grammar mask (``engine/grammar.py``); the
                           ``fallback`` action degrades the request to
                           unconstrained decode + post-hoc validation
                           (recording ``grammar.fallback_failpoint``), and a
                           ``raise`` spec simulates a grammar compile error
                           (caught in-module, recorded as
                           ``grammar.fallback_error``) — the contract under
                           drill is that constrained decoding never errors a
                           request
- ``continuous.step``    — evaluated inside the continuous decode loop's
                           per-step device dispatch (``engine/continuous.py``),
                           i.e. under the loop watchdog's step budget; a
                           ``hang`` spec wedges the dispatch so the watchdog
                           must epoch-fence the abandoned thread, rebuild the
                           engine, and replay the journaled in-flight rows
- ``continuous.prefill`` — evaluated inside the continuous loop's chunked-
                           prefill device dispatch (``engine/continuous.py``),
                           i.e. once per prompt chunk under the same watchdog
                           budget as a decode step; a ``hang`` spec wedges the
                           chunk mid-prompt so recovery must epoch-fence the
                           abandoned thread, rebuild, and REPLAY the
                           half-prefilled admission from cursor 0 with
                           byte-identical output
- ``continuous.worker``  — evaluated at the top of every continuous-loop
                           worker iteration, OUTSIDE the step-level error
                           guard; the ``crash`` action kills the worker thread
                           itself so crash containment must flush every queued
                           and in-flight future with a typed error and restart
                           the loop (bounded by ``max_rebuilds``)
- ``serving.trace``      — evaluated when the tracer starts a request trace
                           (``observability/trace.py``); the ``drop`` action
                           degrades the tracer to no-op spans for that
                           request (no timings, no flight record) while the
                           request itself completes untouched — the contract
                           under drill is that tracing never fails a request
- ``scheduler.tenant``   — evaluated (keyed by tenant name) when the
                           scheduler charges a request against its tenant's
                           token buckets (``engine/scheduler.py``); the
                           ``exhaust`` action forces a quota miss for the
                           named tenant so the typed 429 path — bucket-refill
                           ``retry_after``, per-tenant shed counters — is
                           exercisable without actually draining a bucket
- ``batch.store``        — evaluated inside every batch job-store journal
                           append (``reliability/jobstore.py``); the ``torn``
                           action writes only a PREFIX of the CRC frame and
                           then raises, leaving exactly the on-disk state a
                           kill mid-append leaves, so torn-tail truncation on
                           recovery is exercisable without killing a process
- ``batch.worker``       — evaluated at the top of every batch-lane worker
                           iteration, after an item is dequeued but BEFORE it
                           is marked started (``serving/batch.py``); the
                           ``crash`` action kills the worker thread itself so
                           crash containment must checkpoint the dequeued
                           item back to pending and the lane's exactly-once
                           recovery must complete the job after restart

Actions (``FailSpec.action``):

- ``"raise"``        — raise ``error_factory()`` (default RuntimeError)
- ``"oom"``          — raise a RESOURCE_EXHAUSTED-shaped RuntimeError matching
                       what jax surfaces on device HBM exhaustion, so the
                       engine's OOM guard (not generic error handling) catches
- ``"sleep"``        — block ``delay`` seconds (deadline-expiry simulation)
- ``"hang"``         — block ``delay`` seconds (default effectively forever);
                       distinct from ``sleep`` so a hung-launch spec reads as
                       what it simulates and defaults to "never returns",
                       which is what the launch watchdog must survive
- ``"kill_samples"`` — no-op at the site itself; the engine reads ``kill`` and
                       ``seed`` and marks that many samples failed
- ``"nan"``          — no-op at the site itself; the engine reads ``kill``
                       (row count) and ``seed`` and poisons that many batch
                       rows' logits with NaN
- ``"corrupt"``      — no-op at the site itself; the loader flips bytes in a
                       param leaf after load so checksum verification trips
- ``"down"``         — raise ``EngineHungError`` (a replica-health error) for
                       the member named by ``member``; other members of the
                       keyed site pass through without consuming ``times``
- ``"fail"``         — raise RuntimeError for the member named by ``member``
                       (generic probe/dispatch failure, keyed like ``down``)
- ``"disconnect"``   — no-op at the site itself; the serving layer reads the
                       spec and simulates the client dropping the connection
                       mid-stream (cancel budget, abort the SSE response)
- ``"leak"``         — no-op at the site itself; the paged-KV release path
                       reads ``kill`` and drops that many pages from the free
                       stack unaccounted (a simulated lost decref)
- ``"fallback"``     — no-op at the site itself; the consumer reads the spec
                       and silently degrades to its host/reference path while
                       recording the fallback counters (device consensus ->
                       host scorer; paged attention -> XLA reference;
                       grammar mask -> unconstrained + post-hoc validation)
- ``"crash"``        — raise a RuntimeError shaped like an unexpected worker
                       death; distinct from ``raise`` so a crash-containment
                       spec reads as what it simulates and so the env syntax
                       defaults to firing once (a crash on *every* iteration
                       is a rebuild storm, not a drill)
- ``"drop"``         — no-op at the site itself; the tracer reads the spec
                       and hands out a no-op trace (spans, annotations, and
                       the flight record all degrade to nothing) while the
                       request proceeds normally
- ``"exhaust"``      — no-op at the site itself; the scheduler's tenant-quota
                       charge reads the spec and treats the named tenant's
                       buckets as empty for that request (typed 429 with the
                       bucket's own refill ``retry_after``), keyed by tenant
                       name like the replica sites
- ``"torn"``         — the job store's journal append reads the spec, writes
                       a partial frame (no fsync), and raises — a simulated
                       power cut mid-write; recovery must truncate the torn
                       tail and re-admit the affected items exactly once

``times`` bounds how often a spec fires (fail-rs' ``N*action``): after that
many evaluations the site reverts to no-op — this is how "backend fails twice
then recovers" retry tests are scripted.

Env syntax (comma-separated):
    KLLMS_FAILPOINTS="backend.dispatch=raise:2,engine.decode=kill_samples:3:7"
    KLLMS_FAILPOINTS="engine.launch=oom:1"
    KLLMS_FAILPOINTS="engine.launch=hang:1:30,engine.logits=nan:2:7"
    KLLMS_FAILPOINTS="loader.params=corrupt:1"
    KLLMS_FAILPOINTS="replica.dispatch=down:r1:2,replica.probe=fail:r1:1"
    KLLMS_FAILPOINTS="serving.request=disconnect:1"
    KLLMS_FAILPOINTS="engine.pages=leak:2"
    KLLMS_FAILPOINTS="consensus.device=fallback:3"
    KLLMS_FAILPOINTS="ops.paged_attn=fallback:2"
    KLLMS_FAILPOINTS="engine.grammar=fallback:1"
    KLLMS_FAILPOINTS="engine.grammar=raise:1"
    KLLMS_FAILPOINTS="continuous.step=hang:1:3"
    KLLMS_FAILPOINTS="continuous.prefill=hang:1:3"
    KLLMS_FAILPOINTS="continuous.worker=crash:1"
    KLLMS_FAILPOINTS="serving.trace=drop:2"
    KLLMS_FAILPOINTS="scheduler.tenant=exhaust:bulk:2"
    KLLMS_FAILPOINTS="batch.store=torn:1"
    KLLMS_FAILPOINTS="batch.worker=crash:1"
where the first numeric arg is ``times`` for
raise/sleep/oom/corrupt/disconnect/fallback/drop/torn/crash specs (crash
defaults to firing once), ``times[:delay]`` for hang, ``kill[:seed]`` for
kill_samples/nan, ``kill`` (pages to drop) for leak, and ``member[:times]``
for down/fail/exhaust (keyed sites: replica sites by replica id,
``scheduler.tenant`` by tenant name).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, Optional

import contextlib

logger = logging.getLogger(__name__)

SITES = (
    "scheduler.admit",
    "engine.launch",
    "engine.decode",
    "engine.logits",
    "engine.pages",
    "loader.params",
    "backend.dispatch",
    "consensus.consolidate",
    "replica.dispatch",
    "replica.probe",
    "serving.request",
    "consensus.device",
    "ops.paged_attn",
    "engine.grammar",
    "continuous.step",
    "continuous.prefill",
    "continuous.worker",
    "serving.trace",
    "scheduler.tenant",
    "batch.store",
    "batch.worker",
)

#: Default "hang" duration: long enough that a watchdog MUST intervene for the
#: test to finish, short enough that a leaked spec can't wedge a CI job past
#: its own timeout.
HANG_DELAY = 3600.0


def _injected_oom() -> BaseException:
    # Mirrors the message jaxlib's XlaRuntimeError carries on HBM exhaustion;
    # the engine's OOM guard matches on the RESOURCE_EXHAUSTED marker, so the
    # injected fault takes exactly the split-and-requeue path a real one would.
    return RuntimeError(
        "RESOURCE_EXHAUSTED: injected device OOM (failpoint): "
        "Out of memory while trying to allocate batch buffers"
    )


@dataclass
class FailSpec:
    # "raise" | "oom" | "sleep" | "hang" | "kill_samples" | "nan" | "corrupt"
    # | "down" | "fail" | "disconnect" | "leak" | "fallback" | "crash"
    # | "drop" | "exhaust" | "torn"
    action: str = "raise"
    error_factory: Callable[[], BaseException] = field(
        default=lambda: RuntimeError("injected failpoint fault")
    )
    times: Optional[int] = None  # fire at most N times; None = every time
    delay: float = 0.0  # for action="sleep"/"hang" (hang defaults to HANG_DELAY)
    kill: int = 0  # kill_samples: samples to mark lost; nan: rows to poison
    seed: int = 0  # deterministic sample-kill / row-poison selection
    member: Optional[str] = None  # keyed sites: only fire for this replica id
    _fired: int = field(default=0, init=False, repr=False)

    def __post_init__(self) -> None:
        if self.action not in (
            "raise",
            "oom",
            "sleep",
            "hang",
            "kill_samples",
            "nan",
            "corrupt",
            "down",
            "fail",
            "disconnect",
            "leak",
            "fallback",
            "crash",
            "drop",
            "exhaust",
            "torn",
        ):
            raise ValueError(f"unknown failpoint action {self.action!r}")
        if self.action == "hang" and self.delay <= 0:
            self.delay = HANG_DELAY


# Import-time module lock: this module configures itself from the env at
# import, before any KLLMS_LOCKCHECK opt-in. Leaf by design — registry
# mutation only, never nested with another lock.
# kllms: ignore[lock-order] — import-time module lock, leaf by design
_lock = threading.Lock()
_registry: Dict[str, FailSpec] = {}


def active() -> bool:
    return bool(_registry)


def fire(site: str) -> Optional[FailSpec]:
    """Evaluate a site. Returns the spec for data-carrying actions
    (``kill_samples``); performs ``raise``/``sleep`` directly. The common
    production path is one falsy dict check."""
    if not _registry:
        return None
    with _lock:
        spec = _registry.get(site)
        if spec is None:
            return None
        if spec.times is not None:
            if spec._fired >= spec.times:
                return None
            spec._fired += 1
    logger.debug("failpoint %s fired (%s)", site, spec.action)
    if spec.action == "raise":
        raise spec.error_factory()
    if spec.action == "crash":
        raise RuntimeError(
            f"injected worker crash (failpoint): site {site} killed its thread"
        )
    if spec.action == "oom":
        raise _injected_oom()
    if spec.action in ("sleep", "hang"):
        time.sleep(spec.delay)
        return None
    return spec  # kill_samples/nan/corrupt/disconnect/torn/...: the site's owner interprets it


def fire_keyed(site: str, key: str) -> Optional[FailSpec]:
    """Evaluate a keyed site (the ``replica.*`` sites, keyed by replica id).

    The spec applies only when its ``member`` is ``None`` or equals ``key``; a
    non-matching member neither fires nor consumes ``times``, so
    ``down:r1:2`` kills exactly two dispatches *on r1* regardless of how many
    healthy-member dispatches are interleaved."""
    if not _registry:
        return None
    with _lock:
        spec = _registry.get(site)
        if spec is None:
            return None
        if spec.member is not None and spec.member != key:
            return None
        if spec.times is not None:
            if spec._fired >= spec.times:
                return None
            spec._fired += 1
    logger.debug("failpoint %s fired for %s (%s)", site, key, spec.action)
    if spec.action == "down":
        # Lazy import: wire depends on nothing here, but keep this module
        # import-light for the production no-op path.
        from ..types.wire import EngineHungError

        raise EngineHungError(f"injected replica fault (failpoint): member {key} is down")
    if spec.action == "fail":
        raise RuntimeError(f"injected replica fault (failpoint): member {key} failed")
    if spec.action == "raise":
        raise spec.error_factory()
    if spec.action == "oom":
        raise _injected_oom()
    if spec.action in ("sleep", "hang"):
        time.sleep(spec.delay)
        return None
    return spec


@contextlib.contextmanager
def failpoints(specs: Dict[str, FailSpec]) -> Iterator[None]:
    """Activate failpoints for a block; restores the previous registry (so
    nested scopes and test isolation compose)."""
    unknown = [s for s in specs if s not in SITES]
    if unknown:
        raise ValueError(f"unknown failpoint site(s) {unknown}; known: {list(SITES)}")
    with _lock:
        prev = dict(_registry)
        _registry.update(specs)
    try:
        yield
    finally:
        with _lock:
            _registry.clear()
            _registry.update(prev)


def clear() -> None:
    with _lock:
        _registry.clear()


def configure_from_env(env: Optional[str] = None) -> None:
    """Parse ``KLLMS_FAILPOINTS`` into the registry (process-wide activation
    for soak/chaos runs). Unknown sites fail loudly — a typo'd site name that
    silently never fires is worse than no injection."""
    raw = env if env is not None else os.getenv("KLLMS_FAILPOINTS", "")
    if not raw:
        return
    specs: Dict[str, FailSpec] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        site, _, rhs = part.partition("=")
        action, *args = rhs.split(":")
        if action in ("kill_samples", "nan"):
            kill = int(args[0]) if args else 1
            seed = int(args[1]) if len(args) > 1 else 0
            specs[site] = FailSpec(action=action, kill=kill, seed=seed)
        elif action == "leak":
            kill = int(args[0]) if args else 1
            specs[site] = FailSpec(action="leak", kill=kill)
        elif action == "sleep":
            delay = float(args[0]) if args else 0.1
            times = int(args[1]) if len(args) > 1 else None
            specs[site] = FailSpec(action="sleep", delay=delay, times=times)
        elif action == "hang":
            times = int(args[0]) if args else 1
            delay = float(args[1]) if len(args) > 1 else HANG_DELAY
            specs[site] = FailSpec(action="hang", times=times, delay=delay)
        elif action in ("oom", "corrupt", "disconnect", "fallback", "drop", "torn"):
            times = int(args[0]) if args else None
            specs[site] = FailSpec(action=action, times=times)
        elif action == "crash":
            # Unbounded crash specs are rebuild storms, not drills: default 1.
            times = int(args[0]) if args else 1
            specs[site] = FailSpec(action="crash", times=times)
        elif action in ("down", "fail", "exhaust"):
            member = args[0] if args and args[0] else None
            times = int(args[1]) if len(args) > 1 else None
            specs[site] = FailSpec(action=action, member=member, times=times)
        else:
            times = int(args[0]) if args else None
            specs[site] = FailSpec(action="raise", times=times)
    unknown = [s for s in specs if s not in SITES]
    if unknown:
        raise ValueError(f"KLLMS_FAILPOINTS names unknown site(s) {unknown}")
    with _lock:
        _registry.update(specs)


configure_from_env()
