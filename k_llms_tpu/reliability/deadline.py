"""Deadlines and per-request budgets.

The reference SDK inherits the OpenAI client's ``timeout=`` wire contract and
request-cancellation machinery for free (PAPER.md §0); a local engine owns the
whole request lifecycle, so the budget object created from ``timeout=`` in the
resources layer must travel down through the scheduler (admission control) and
into the engine's decode loop (token-granularity cancellation) and be checkable
at each stage without re-deriving wall-clock math.

``Deadline`` is a plain absolute-monotonic instant (``math.inf`` when no
timeout was given). ``RequestBudget`` couples a deadline with a cooperative
cancel token; every layer calls ``check(stage)`` (raises the typed error) or
``should_abort()`` (bool poll, used between decode steps where raising inside
jitted code is impossible).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional

from ..types.wire import RequestCancelledError, RequestTimeoutError


class Deadline:
    """Absolute monotonic-clock deadline; infinite when no timeout applies."""

    __slots__ = ("at",)

    def __init__(self, at: float = math.inf):
        self.at = float(at)

    @classmethod
    def from_timeout(cls, timeout: Optional[float]) -> "Deadline":
        if timeout is None:
            return cls(math.inf)
        if timeout < 0:
            raise ValueError(f"timeout must be >= 0, got {timeout}")
        return cls(time.monotonic() + timeout)

    @property
    def finite(self) -> bool:
        return math.isfinite(self.at)

    def remaining(self) -> float:
        """Seconds until expiry; ``inf`` when no timeout, <= 0 when expired."""
        return self.at - time.monotonic()

    def expired(self) -> bool:
        return time.monotonic() >= self.at

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Deadline(remaining={self.remaining():.3f}s)" if self.finite else "Deadline(inf)"


class RequestBudget:
    """One request's lifecycle budget: a deadline plus a cancel token.

    Created in the resources layer from ``timeout=`` (or passed in by a caller
    who wants to hold the cancel handle), then threaded through scheduler
    admission, backend dispatch, and the engine decode loop. Thread-safe: the
    cancel token is an event, the deadline is immutable.
    """

    __slots__ = ("deadline", "_cancelled")

    def __init__(self, deadline: Optional[Deadline] = None):
        self.deadline = deadline or Deadline()
        self._cancelled = threading.Event()

    @classmethod
    def from_timeout(cls, timeout: Optional[float]) -> "RequestBudget":
        return cls(Deadline.from_timeout(timeout))

    # -- cancellation -----------------------------------------------------
    def cancel(self) -> None:
        """Cooperatively cancel: queued work is shed at admission, in-flight
        decode stops at the next token boundary."""
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    # -- polling ----------------------------------------------------------
    @property
    def finite(self) -> bool:
        """Whether this budget can ever abort (deadline set or cancellable —
        a cancel token always makes it worth polling)."""
        return True

    def expired(self) -> bool:
        return self.deadline.expired()

    def should_abort(self) -> bool:
        return self._cancelled.is_set() or self.deadline.expired()

    def remaining(self) -> float:
        return self.deadline.remaining()

    def error(self, stage: str = "") -> Exception:
        """The typed error describing WHY this budget aborted (cancel wins:
        it is the caller's explicit signal, deadline expiry is incidental)."""
        where = f" at {stage}" if stage else ""
        if self._cancelled.is_set():
            return RequestCancelledError(f"request cancelled{where}")
        return RequestTimeoutError(
            f"request deadline exceeded{where} (budget expired)"
        )

    def check(self, stage: str = "") -> None:
        """Raise the typed error if the budget is spent; no-op otherwise."""
        if self.should_abort():
            raise self.error(stage)
