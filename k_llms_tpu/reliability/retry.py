"""Bounded retry with exponential backoff + jitter, and a per-backend circuit
breaker.

The reference gets retries from the OpenAI client (2 retries, exponential
backoff); locally the same shape already proved itself in ``bench.py``'s
relay-flap survival (bounded probe attempts + backoff + structured error on
final failure). This module is that shape as a reusable policy, plus the
circuit breaker that turns a flapping backend (relay death, OOM loop, compile
failure storm) into fast typed errors instead of every caller queueing behind
a hang.

Determinism: jitter derives from ``random.Random(seed)`` so failure tests can
pin exact backoff schedules; production constructs without a seed.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple, Type, TypeVar

from ..analysis.lockcheck import make_lock
from ..types.wire import BackendUnavailableError, KLLMsError
from ..utils.observability import FAILURE_EVENTS
from .deadline import RequestBudget

logger = logging.getLogger(__name__)

T = TypeVar("T")

# Typed lifecycle errors and parameter errors must NEVER be retried: the
# former are final verdicts (deadline/cancel/circuit), the latter are caller
# bugs that will fail identically on every attempt.
NON_RETRYABLE: Tuple[Type[BaseException], ...] = (
    KLLMsError,
    ValueError,
    TypeError,
    KeyboardInterrupt,
)


def is_retryable(exc: BaseException) -> bool:
    return not isinstance(exc, NON_RETRYABLE)


@dataclass
class RetryPolicy:
    """Exponential backoff with full jitter: delay_k = U(0, min(cap, base*2^k)).

    ``max_attempts`` counts total tries (1 = no retry). Sleeps are bounded by
    the request budget's remaining time — a retry never outlives the deadline
    it is trying to beat.
    """

    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    jitter: bool = True
    seed: Optional[int] = None
    _rng: random.Random = field(init=False, repr=False, compare=False, default=None)  # type: ignore[assignment]

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)

    def delay_for(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based: after the first
        failure attempt=1)."""
        cap = min(self.max_delay, self.base_delay * (2 ** max(0, attempt - 1)))
        return self._rng.uniform(0.0, cap) if self.jitter else cap

    def call(
        self,
        fn: Callable[[], T],
        budget: Optional[RequestBudget] = None,
        on_retry: Optional[Callable[[BaseException, int], None]] = None,
        sleep: Callable[[float], None] = time.sleep,
    ) -> T:
        """Run ``fn`` under this policy. Non-retryable errors and budget
        expiry propagate immediately; the final attempt's error propagates
        as-is (callers wrap it in their own typed error if they want one)."""
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            if budget is not None:
                budget.check("retry")
            try:
                return fn()
            except BaseException as e:
                if not is_retryable(e) or attempt >= self.max_attempts:
                    raise
                last = e
                FAILURE_EVENTS.record("retry.attempt")
                if on_retry is not None:
                    on_retry(e, attempt)
                delay = self.delay_for(attempt)
                if budget is not None:
                    remaining = budget.remaining()
                    if remaining <= 0:
                        raise
                    delay = min(delay, max(0.0, remaining))
                logger.debug(
                    "retry %d/%d after %r; backing off %.3fs",
                    attempt, self.max_attempts, e, delay,
                )
                if delay > 0:
                    sleep(delay)
        raise last  # pragma: no cover - loop always returns or raises


class CircuitBreaker:
    """Per-backend circuit breaker: closed -> open after ``failure_threshold``
    consecutive failures; open sheds calls instantly with a typed
    ``BackendUnavailableError``; after ``reset_timeout`` seconds ONE probe call
    is admitted (half-open) — success closes the circuit, failure re-opens it.

    ``clock`` is injectable so tests pin transitions without sleeping.
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_timeout: float = 10.0,
        name: str = "backend",
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.name = name
        self._clock = clock
        self._lock = make_lock(f"reliability.breaker.{name}" if name else "reliability.breaker")
        self._failures = 0
        self._state = "closed"  # closed | open | half_open
        self._opened_at = 0.0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> None:
        """Gate a dispatch: raises ``BackendUnavailableError`` when open (and
        not yet due for a probe); transitions open -> half_open when due."""
        with self._lock:
            if self._state == "closed":
                return
            if self._state == "open":
                if self._clock() - self._opened_at >= self.reset_timeout:
                    self._state = "half_open"
                    logger.info("circuit %s: open -> half_open (probe admitted)", self.name)
                    return
                FAILURE_EVENTS.record("circuit.rejected")
                raise BackendUnavailableError(
                    f"backend {self.name!r} circuit open after "
                    f"{self._failures} consecutive failures; retrying in "
                    f"{max(0.0, self.reset_timeout - (self._clock() - self._opened_at)):.1f}s"
                )
            # half_open: exactly one probe in flight is the simple (and
            # sufficient) policy — concurrent callers shed fast.
            FAILURE_EVENTS.record("circuit.rejected")
            raise BackendUnavailableError(
                f"backend {self.name!r} circuit half-open; probe in flight"
            )

    def record_success(self) -> None:
        with self._lock:
            if self._state != "closed":
                logger.info("circuit %s: %s -> closed", self.name, self._state)
            self._failures = 0
            self._state = "closed"

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == "half_open" or self._failures >= self.failure_threshold:
                if self._state != "open":
                    logger.warning(
                        "circuit %s: -> open after %d consecutive failures",
                        self.name, self._failures,
                    )
                    FAILURE_EVENTS.record("circuit.opened")
                self._state = "open"
                self._opened_at = self._clock()
