"""Self-healing engine supervision: hung-launch watchdog + rebuild/replay.

PRs 1-2 hardened the *request* path (deadlines, retries, breakers, bounded
admission); the engine itself remained a single point of failure. A wedged
XLA launch never returns — no exception, no timeout — so the scheduler worker
blocks forever and every queued request hangs behind it. This module closes
that gap with the supervision pattern production engines use:

- **Watchdog**: every device launch runs on a disposable daemon thread under
  a wall-clock budget derived from the batch's token budget and a measured
  per-token latency EWMA (:class:`LaunchBudgetModel`). An overdue launch is
  declared hung; the supervisor detaches from it and keeps control of the
  caller's futures.
- **Epoch fencing**: the supervisor bumps a replay epoch the moment a launch
  is declared hung. The abandoned thread checks the epoch when (if ever) it
  completes and discards its result instead of racing the replay — the
  idempotency half of replay semantics.
- **Rebuild + replay**: a hung (or poison-escalated) engine is torn down and
  rebuilt through a caller-supplied ``rebuild_fn`` (recompile + param reload
  through the existing loader), then the SAME launch closure is re-invoked.
  Sampling seeds are pinned at submission time (see
  ``TpuBackend._generate_batched``), so a replay on identical weights is
  byte-identical to an uninterrupted run — the determinism half.
- **Bounded escalation**: consecutive rebuilds without a successful launch
  are bounded; exhaustion (or a corrupt checkpoint on reload) is terminal —
  the scheduler is moved to STOPPED and callers get typed 503s.

The supervisor runs entirely on the scheduler worker thread (the launch
thread is the only thing it spawns), so no new synchronization is imposed on
the engine: at most one launch/rebuild is ever active.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Optional, Tuple

from ..analysis.lockcheck import make_lock
from ..types.wire import CheckpointCorruptError, EngineHungError
from ..utils.observability import RECOVERY_EVENTS

logger = logging.getLogger(__name__)


class LaunchBudgetModel:
    """Wall-clock budget for one device launch.

    ``budget = clamp(base + multiplier * max_new_tokens * per_token_ewma)``

    ``per_token_ewma`` is learned from completed launches (elapsed divided by
    the batch's max_new_tokens — decode steps dominate, and step latency is
    nearly row-count independent at serving widths, so tokens are the right
    unit). The generous ``min_budget`` floor absorbs first-launch compile
    time, which the EWMA then decays away from; ``multiplier`` is the slack
    between "slow" and "hung".
    """

    def __init__(
        self,
        base_s: float = 10.0,
        per_token_s: float = 0.5,
        multiplier: float = 8.0,
        min_budget_s: float = 60.0,
        max_budget_s: float = 900.0,
        ewma_alpha: float = 0.3,
    ) -> None:
        self.base_s = base_s
        self.multiplier = multiplier
        self.min_budget_s = min_budget_s
        self.max_budget_s = max_budget_s
        self.ewma_alpha = ewma_alpha
        self._lock = make_lock("reliability.launch_budget")
        self._per_token_s = per_token_s
        self._observed = 0

    def budget(self, rows: int, max_new_tokens: int) -> float:
        with self._lock:
            per_token = self._per_token_s
        raw = self.base_s + self.multiplier * max(1, max_new_tokens) * per_token
        return min(self.max_budget_s, max(self.min_budget_s, raw))

    def observe(self, rows: int, max_new_tokens: int, elapsed_s: float) -> None:
        sample = elapsed_s / max(1, max_new_tokens)
        with self._lock:
            if self._observed == 0:
                self._per_token_s = sample
            else:
                a = self.ewma_alpha
                self._per_token_s = a * sample + (1.0 - a) * self._per_token_s
            self._observed += 1

    # -- per-step budget (continuous decode loop) --------------------------
    #
    # The continuous loop's unit of dispatch is one STEP — a single token
    # across every active slot row — so its watchdog budget is the
    # max_new_tokens=1 specialization of the launch budget: the same EWMA,
    # the same clamp, learned one step at a time. The floor still absorbs
    # first-step compile (a new batch shape recompiles mid-loop).

    def step_budget(self) -> float:
        return self.budget(1, 1)

    def observe_step(self, elapsed_s: float) -> None:
        self.observe(1, 1, elapsed_s)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "per_token_s": round(self._per_token_s, 6),
                "observed_launches": self._observed,
            }


class EngineSupervisor:
    """Runs device launches under a watchdog and heals the engine when one
    hangs or numeric poison crosses the escalation threshold.

    ``rebuild_fn`` tears down and reconstructs the engine (the launch closure
    must re-resolve the engine at call time so a replay lands on the rebuilt
    one). The ``on_recovering``/``on_rebuilt``/``on_rebuild_failed`` hooks are
    the scheduler's RECOVERING / READY / STOPPED transitions.
    """

    def __init__(
        self,
        rebuild_fn: Callable[[], None],
        budget_model: Optional[LaunchBudgetModel] = None,
        max_rebuilds: int = 2,
        poison_threshold: float = 0.5,
        poison_window: int = 8,
        on_recovering: Optional[Callable[[int, str], None]] = None,
        on_rebuilt: Optional[Callable[[], None]] = None,
        on_rebuild_failed: Optional[Callable[[BaseException], None]] = None,
    ) -> None:
        self.rebuild_fn = rebuild_fn
        self.budget_model = budget_model or LaunchBudgetModel()
        self.max_rebuilds = max_rebuilds
        self.poison_threshold = poison_threshold
        self.on_recovering = on_recovering
        self.on_rebuilt = on_rebuilt
        self.on_rebuild_failed = on_rebuild_failed
        self._lock = make_lock("reliability.supervisor")
        self._epoch = 0
        self._consecutive_rebuilds = 0
        self._total_rebuilds = 0
        self._hung_launches = 0
        self._replayed = 0
        self._rebuild_wanted: Optional[str] = None
        self._terminal_error: Optional[BaseException] = None
        self._last_rebuild_reason: Optional[str] = None
        # (poisoned, total) per recent launch; escalation looks at the
        # aggregate fraction so one bad launch among many clean ones
        # doesn't trigger a rebuild.
        self._poison_history: Deque[Tuple[int, int]] = deque(maxlen=max(1, poison_window))

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    # -- numeric-integrity escalation -------------------------------------

    def note_poison(self, poisoned: int, total: int) -> None:
        """Record one launch's quarantine outcome (poisoned rows out of
        total). Called from the engine's ``on_quarantine`` hook — including
        with ``poisoned=0`` for clean launches, so the window decays."""
        if total <= 0:
            return
        with self._lock:
            self._poison_history.append((int(poisoned), int(total)))
            bad = sum(p for p, _ in self._poison_history)
            seen = sum(t for _, t in self._poison_history)
            if seen > 0 and bad / seen >= self.poison_threshold and bad > 0:
                if self._rebuild_wanted is None:
                    logger.warning(
                        "poison rate %.2f over last %d launches >= %.2f: "
                        "escalating to engine rebuild",
                        bad / seen,
                        len(self._poison_history),
                        self.poison_threshold,
                    )
                self._rebuild_wanted = "poison_rate"

    # -- supervised launch --------------------------------------------------

    def supervised_launch(
        self,
        launch_fn: Callable[[], Any],
        rows: int = 1,
        max_new_tokens: int = 1,
    ) -> Any:
        """Run ``launch_fn`` under the watchdog; heal and replay on hang.

        Returns the launch's result (possibly from a replay on a rebuilt
        engine) or re-raises its exception. Raises :class:`EngineHungError`
        (or :class:`CheckpointCorruptError` from the reload) only when
        recovery is exhausted — that is the terminal path."""
        with self._lock:
            if self._terminal_error is not None:
                raise EngineHungError(
                    "engine supervisor is stopped after exhausting rebuild "
                    f"attempts: {self._terminal_error}"
                )
        replay = False
        while True:
            wanted = self._take_rebuild_wanted()
            if wanted is not None:
                self._rebuild(reason=wanted)
            budget = self.budget_model.budget(rows, max_new_tokens)
            start_epoch = self.epoch
            done = threading.Event()
            box: Dict[str, Any] = {}

            def _run(_epoch: int = start_epoch, _box: Dict[str, Any] = box, _done: threading.Event = done) -> None:
                try:
                    _box["result"] = launch_fn()
                except BaseException as exc:  # delivered to the caller below
                    _box["error"] = exc
                finally:
                    with self._lock:
                        stale = self._epoch != _epoch
                    if stale:
                        # The watchdog already declared this launch hung and
                        # moved on; its late result must not race the replay.
                        RECOVERY_EVENTS.record("supervisor.stale_results_discarded")
                        logger.warning(
                            "discarding stale result from hung launch (epoch %d < %d)",
                            _epoch,
                            self.epoch,
                        )
                    _done.set()

            started = time.monotonic()
            thread = threading.Thread(
                target=_run, name="kllms-supervised-launch", daemon=True
            )
            thread.start()
            if done.wait(budget):
                elapsed = time.monotonic() - started
                if "error" in box:
                    raise box["error"]
                self.budget_model.observe(rows, max_new_tokens, elapsed)
                with self._lock:
                    self._consecutive_rebuilds = 0
                if replay:
                    with self._lock:
                        self._replayed += rows
                    RECOVERY_EVENTS.record("supervisor.replayed", rows)
                return box["result"]
            # Hung: fence the epoch FIRST so the abandoned thread's eventual
            # result is discarded, then heal and replay.
            with self._lock:
                self._epoch += 1
                self._hung_launches += 1
            RECOVERY_EVENTS.record("supervisor.hung_launches")
            logger.error(
                "device launch exceeded its %.1fs watchdog budget "
                "(rows=%d, max_new_tokens=%d): declaring hung and rebuilding",
                budget,
                rows,
                max_new_tokens,
            )
            self._rebuild(reason="hung_launch")
            replay = True

    # -- rebuild ------------------------------------------------------------

    def _take_rebuild_wanted(self) -> Optional[str]:
        with self._lock:
            wanted, self._rebuild_wanted = self._rebuild_wanted, None
            return wanted

    def _rebuild(self, reason: str) -> None:
        with self._lock:
            self._consecutive_rebuilds += 1
            self._total_rebuilds += 1
            attempt = self._consecutive_rebuilds
            self._last_rebuild_reason = reason
            self._poison_history.clear()
            self._rebuild_wanted = None
        if attempt > self.max_rebuilds:
            self._terminal(
                EngineHungError(
                    f"engine did not recover after {self.max_rebuilds} rebuild "
                    f"attempt(s) (last reason: {reason})"
                )
            )
        if self.on_recovering is not None:
            self.on_recovering(attempt, reason)
        RECOVERY_EVENTS.record("supervisor.rebuilds")
        logger.warning("rebuilding engine (attempt %d/%d, reason=%s)", attempt, self.max_rebuilds, reason)
        try:
            self.rebuild_fn()
        except BaseException as exc:
            RECOVERY_EVENTS.record("supervisor.rebuild_failures")
            # A corrupt checkpoint can never be healed by retrying the
            # rebuild — fail fast with the precise error.
            if isinstance(exc, CheckpointCorruptError):
                self._terminal(exc)
            self._terminal(
                EngineHungError(f"engine rebuild failed (reason: {reason}): {exc}")
            )
        if self.on_rebuilt is not None:
            self.on_rebuilt()

    def _terminal(self, error: BaseException) -> None:
        with self._lock:
            self._terminal_error = error
        if self.on_rebuild_failed is not None:
            self.on_rebuild_failed(error)
        raise error

    # -- introspection ------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "epoch": self._epoch,
                "hung_launches": self._hung_launches,
                "rebuilds": self._total_rebuilds,
                "consecutive_rebuilds": self._consecutive_rebuilds,
                "max_rebuilds": self.max_rebuilds,
                "replayed": self._replayed,
                "last_rebuild_reason": self._last_rebuild_reason,
                "stopped": self._terminal_error is not None,
                "launch_budget": self.budget_model.stats(),
            }
