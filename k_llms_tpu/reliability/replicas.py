"""Replica-set serving: health-aware routing, mid-flight failover, hedging.

The single-engine reliability stack (deadlines + breakers, overload shedding,
self-healing supervision) still serializes every caller behind one engine's
bad day: while the supervisor rebuilds a hung engine, all queued work waits.
:class:`ReplicaSet` is the standard serving-stack answer — N member backends
behind the one :class:`Backend` surface, so ``resolve_backend("replicas",
members=[...])`` is a drop-in for the client and resources layer.

Three mechanisms, in dispatch order:

1. **Health-aware routing.** Each dispatch goes to the eligible member with
   the lowest score ``latency_EWMA × (1 + queue_load)``, with multiplicative
   penalties for DEGRADED state and half-open breakers. A member whose
   supervisor reports RECOVERING/DRAINING/STOPPED — or whose dispatch just
   died with a replica-health error — leaves rotation and rejoins only after
   a synthetic health-probe generation passes (``probe()``), never merely
   because time passed.

2. **Mid-flight failover.** A dispatch that dies with a replica-health error
   (EngineHungError, terminal OOM, connection loss…) is transparently
   re-dispatched to a survivor. The set pins the request seed *before* the
   first attempt (the same pinning the supervisor relies on for replay), so
   the failover rerun is byte-identical to an uninterrupted run on the
   survivor. Bounded by ``max_failover_attempts`` and the caller's budget;
   caller-owned outcomes (timeout, cancel) and caller bugs (ValueError…)
   never fail over.

3. **Hedged dispatch** ("The Tail at Scale"). When the primary has not
   answered after a delay derived from its observed p95 latency, the launch
   is duplicated on a second healthy member. First result wins; the loser's
   child budget is cancelled, which the engine's io_callback abort poller
   turns into a token-granularity decode abort. Hedge attempts call the
   member's raw ``chat_completion`` (not ``dispatch_chat_completion``), so a
   losing or failing hedge never counts against any circuit breaker.

Degradation is honest: zero eligible members ⇒ :class:`NoHealthyReplicasError`
(an OpenAI-wire 503) listing the per-replica reasons, and when the surviving
capacity sheds with 429s the ``retry_after`` estimate is scaled by
``total_members / healthy_members`` so callers back off proportionally to the
capacity actually lost.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from collections import deque
from concurrent.futures import FIRST_COMPLETED, Future, ThreadPoolExecutor, wait
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

from ..analysis.lockcheck import make_lock, race_exempt
from ..backends.base import Backend, ChatRequest
from ..types import ChatCompletion
from ..types.wire import (
    NoHealthyReplicasError,
    RateLimitError,
    RequestCancelledError,
    RequestTimeoutError,
)
from ..utils.observability import FAILOVER_EVENTS, HEDGE_EVENTS, ROUTE_EVENTS
from . import failpoints as _failpoints
from .deadline import RequestBudget

logger = logging.getLogger(__name__)

#: Backend health states that pull a member from rotation (supervisor is
#: rebuilding it, it is draining, or it is gone). DEGRADED stays in rotation —
#: a width-backed-off engine still serves — it just scores worse.
_OUT_OF_ROTATION_STATES = ("recovering", "draining", "stopped")

#: Errors that are the CALLER's outcome (their deadline/cancel) or the
#: caller's bug — never a replica-health signal, so never a failover trigger.
_NO_FAILOVER = (
    RequestTimeoutError,
    RequestCancelledError,
    ValueError,
    TypeError,
    KeyboardInterrupt,
)


class ReplicaHandle:
    """One member of a :class:`ReplicaSet` plus its routing state: latency
    EWMA + recent-sample window (for the hedge-delay p95), dispatch tallies,
    and the in/out-of-rotation probation state."""

    def __init__(self, replica_id: str, backend: Backend):
        self.replica_id = replica_id
        self.backend = backend
        self.lock = make_lock(f"reliability.replica.{replica_id}")
        self._ewma_s: Optional[float] = None
        self._recent: "deque[float]" = deque(maxlen=64)
        self.dispatched = 0
        self.failed = 0
        self.hedges_won = 0
        self.failovers = 0
        self.in_rotation = True
        self.out_reason: Optional[str] = None
        self.out_since: Optional[float] = None
        self.probe_failures = 0
        self.last_probe_at = 0.0  # monotonic; 0 = never probed
        self.probing = False  # an async probe is in flight

    # -- latency ----------------------------------------------------------
    def note_success(self, elapsed_s: float) -> None:
        with self.lock:
            self.dispatched += 1
            self._recent.append(elapsed_s)
            alpha = 0.3
            self._ewma_s = (
                elapsed_s
                if self._ewma_s is None
                else alpha * elapsed_s + (1 - alpha) * self._ewma_s
            )

    def note_failure(self) -> None:
        with self.lock:
            self.failed += 1

    def ewma_s(self) -> Optional[float]:
        with self.lock:
            return self._ewma_s

    def p95_s(self) -> Optional[float]:
        """p95 of the recent-latency window; None until enough history exists
        to call anything a tail (hedging without history would just double
        every launch)."""
        with self.lock:
            if len(self._recent) < 4:
                return None
            ordered = sorted(self._recent)
            return ordered[min(len(ordered) - 1, int(0.95 * (len(ordered) - 1)))]

    # -- rotation ----------------------------------------------------------
    def mark_down(self, reason: str) -> None:
        with self.lock:
            if self.in_rotation:
                self.in_rotation = False
                self.out_since = time.monotonic()
            self.out_reason = reason[:200]

    def rejoin(self) -> None:
        with self.lock:
            self.in_rotation = True
            self.out_reason = None
            self.out_since = None
            self.probe_failures = 0

    def safe_health(self) -> Dict[str, Any]:
        try:
            return self.backend.health()
        except BaseException as e:  # a member too sick to report health
            return {"state": f"health_error:{type(e).__name__}", "breaker": "open"}


class ReplicaSet(Backend):
    """N member backends behind one :class:`Backend` surface.

    ``members`` accepts Backend instances (tests, pre-built engines), backend
    names (each resolved via :func:`resolve_backend` with ``**member_kwargs``),
    or per-member dicts ``{"backend": "tpu", "id": "west", **kwargs}`` for
    heterogeneous sets. Replica ids default to ``r0..rN-1``.

    Routing knobs:

    - ``route_policy``: ``"health"`` (default — score-based) or
      ``"round_robin"`` (uniform over eligible members; used by benchmarks
      that must not let the EWMA route around an injected slow member).
    - ``hedge`` / ``hedge_delay_s`` / ``hedge_latency_multiplier``: hedging
      on/off, a fixed hedge delay, or (default) ``p95 × multiplier`` from the
      primary's observed latency window.
    - ``max_failover_attempts``: additional members tried after the primary's
      replica-health failure.
    - ``probe_interval_s`` / ``probe_timeout_s`` / ``probe_max_tokens``: the
      synthetic health-probe generation gating rejoin.
    """

    def __init__(
        self,
        members: Optional[Sequence[Union[Backend, str, Dict[str, Any]]]] = None,
        *,
        model: Optional[str] = None,
        route_policy: str = "health",
        hedge: bool = True,
        hedge_delay_s: Optional[float] = None,
        hedge_latency_multiplier: float = 2.0,
        min_hedge_delay_s: float = 0.05,
        max_failover_attempts: int = 2,
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 30.0,
        probe_max_tokens: int = 4,
        **member_kwargs: Any,
    ):
        if not members:
            raise ValueError(
                "ReplicaSet needs at least one member; pass members=[...] "
                "(Backend instances, backend names, or per-member dicts)"
            )
        if route_policy not in ("health", "round_robin"):
            raise ValueError(
                f"route_policy must be 'health' or 'round_robin', got {route_policy!r}"
            )
        self.route_policy = route_policy
        self.hedge = hedge
        self.hedge_delay_s = hedge_delay_s
        self.hedge_latency_multiplier = hedge_latency_multiplier
        self.min_hedge_delay_s = min_hedge_delay_s
        self.max_failover_attempts = max_failover_attempts
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.probe_max_tokens = probe_max_tokens

        handles: List[ReplicaHandle] = []
        for i, member in enumerate(members):
            replica_id = f"r{i}"
            if isinstance(member, Backend):
                backend = member
            elif isinstance(member, str):
                backend = self._build_member(member, model, member_kwargs)
            elif isinstance(member, dict):
                spec = dict(member)
                replica_id = str(spec.pop("id", replica_id))
                name = spec.pop("backend", "tpu")
                backend = self._build_member(name, model, {**member_kwargs, **spec})
            else:
                raise TypeError(
                    f"member {i} must be a Backend, backend name, or dict, "
                    f"got {type(member).__name__}"
                )
            handles.append(ReplicaHandle(replica_id, backend))
        ids = [h.replica_id for h in handles]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self._handles = handles
        self._by_id = {h.replica_id: h for h in handles}
        self.model_name = (
            model or getattr(handles[0].backend, "model_name", None) or "replicas"
        )
        self._rr_lock = make_lock("reliability.replica_rr")
        self._rr_next = 0
        # Monotonic shutdown latch: a stale False costs at most one extra
        # probe submission, which the shut-down executor rejects harmlessly.
        # kllms: unguarded — monotonic shutdown latch; stale reads are benign
        self._closed = False
        race_exempt(self, "_closed")
        # Sized for hedged dispatch (2 workers per in-flight hedged request)
        # plus background probes. The wait loop runs on the caller's thread,
        # never in this pool, so saturation queues work instead of deadlocking.
        self._executor = ThreadPoolExecutor(
            max_workers=max(8, 4 * len(handles)),
            thread_name_prefix="kllms-replica",
        )

    @staticmethod
    def _build_member(
        name: str, model: Optional[str], kwargs: Dict[str, Any]
    ) -> Backend:
        from ..backends.base import resolve_backend

        kw = dict(kwargs)
        if model is not None:
            kw.setdefault("model", model)
        return resolve_backend(name, **kw)

    # -- routing -----------------------------------------------------------
    def _score(self, handle: ReplicaHandle, snap: Dict[str, Any]) -> float:
        """Lower is better: latency EWMA scaled by queue pressure, penalized
        for degraded state and a half-open (probing) breaker."""
        ewma = handle.ewma_s() or 0.050  # optimistic prior for cold members
        try:
            load = int(snap.get("queue_weight") or snap.get("queue_depth") or 0)
            load += int(snap.get("in_flight") or 0)
        except (TypeError, ValueError):
            load = 0
        score = ewma * (1.0 + load)
        if str(snap.get("state")) == "degraded":
            score *= 2.0
        if str(snap.get("breaker")) == "half_open":
            score *= 4.0
        return score

    def _eligible(
        self, exclude: frozenset
    ) -> Tuple[List[Tuple[ReplicaHandle, float]], Dict[str, str]]:
        """Eligible members with scores, plus per-replica reasons for every
        ineligible one (the 503 body). Side effects: pulls members whose
        backend reports an out-of-rotation state, and kicks off async probes
        for members sitting in probation."""
        eligible: List[Tuple[ReplicaHandle, float]] = []
        reasons: Dict[str, str] = {}
        for handle in self._handles:
            snap = handle.safe_health()
            state = str(snap.get("state", "ready"))
            with handle.lock:
                in_rotation = handle.in_rotation
            if in_rotation and state in _OUT_OF_ROTATION_STATES:
                handle.mark_down(f"backend state: {state}")
                in_rotation = False
                ROUTE_EVENTS.record("route.pulled")
                logger.warning(
                    "replica %s pulled from rotation (state=%s)",
                    handle.replica_id,
                    state,
                )
            if not in_rotation:
                with handle.lock:
                    out_reason = handle.out_reason
                reasons[handle.replica_id] = out_reason or "out of rotation"
                self._maybe_probe_async(handle)
                continue
            if handle.replica_id in exclude:
                reasons[handle.replica_id] = "already tried for this request"
                continue
            breaker = str(snap.get("breaker", handle.backend.circuit_breaker.state))
            if breaker == "open":
                reasons[handle.replica_id] = "circuit breaker open"
                continue
            eligible.append((handle, self._score(handle, snap)))
        return eligible, reasons

    def _route(
        self, exclude: frozenset = frozenset(), advance_round_robin: bool = True
    ) -> ReplicaHandle:
        """Pick the best eligible member. With zero eligible members, try one
        synchronous probe round over probation members (rate-limited by
        ``probe_interval_s``) before giving up with the typed 503.
        ``advance_round_robin=False`` (hedge routing) keeps the round-robin
        cursor aligned with primary dispatches."""
        eligible, reasons = self._eligible(exclude)
        if not eligible:
            for handle in self._handles:
                with handle.lock:
                    in_rotation = handle.in_rotation
                    last_probe_at = handle.last_probe_at
                if in_rotation or handle.replica_id in exclude:
                    continue
                if time.monotonic() - last_probe_at < self.probe_interval_s:
                    continue
                if self._probe(handle):
                    return handle
            ROUTE_EVENTS.record("route.no_healthy")
            detail = "; ".join(f"{rid}: {why}" for rid, why in sorted(reasons.items()))
            raise NoHealthyReplicasError(
                f"no healthy replicas ({len(self._handles)} members): {detail}",
                reasons=reasons,
            )
        if self.route_policy == "round_robin":
            with self._rr_lock:
                start = self._rr_next
                if advance_round_robin:
                    self._rr_next += 1
            order = {h.replica_id: i for i, h in enumerate(self._handles)}
            ranked = sorted(eligible, key=lambda t: order[t[0].replica_id])
            return ranked[start % len(ranked)][0]
        return min(eligible, key=lambda t: t[1])[0]

    # -- probes ------------------------------------------------------------
    def _maybe_probe_async(self, handle: ReplicaHandle) -> None:
        with handle.lock:
            if handle.probing or self._closed:
                return
            if time.monotonic() - handle.last_probe_at < self.probe_interval_s:
                return
            handle.probing = True

        def run() -> None:
            try:
                self._probe(handle)
            finally:
                with handle.lock:
                    handle.probing = False

        try:
            self._executor.submit(run)
        except RuntimeError:  # executor shut down during close/drain
            with handle.lock:
                handle.probing = False

    def probe(self, replica_id: str) -> bool:
        """Synchronously run the health probe for one member (public for tests
        and operator tooling); True means the member passed and rejoined."""
        return self._probe(self._by_id[replica_id])

    def _probe(self, handle: ReplicaHandle) -> bool:
        """The rejoin gate: a member in probation must answer a real (tiny,
        greedy, deadline-bounded) generation before it serves traffic again.
        A passing probe also records a breaker success, so a half-open
        circuit closes off the probe rather than off a user request."""
        with handle.lock:
            handle.last_probe_at = time.monotonic()
        ROUTE_EVENTS.record("route.probes")
        try:
            _failpoints.fire_keyed("replica.probe", handle.replica_id)
            snap = handle.safe_health()
            state = str(snap.get("state", "ready"))
            if state in _OUT_OF_ROTATION_STATES or state.startswith("health_error"):
                raise RuntimeError(f"probe: backend state is {state}")
            request = ChatRequest(
                messages=[{"role": "user", "content": "replica health probe"}],
                model=self.model_name,
                n=1,
                max_tokens=self.probe_max_tokens,
                temperature=0.0,
                seed=0,
                budget=RequestBudget.from_timeout(self.probe_timeout_s),
            )
            out = handle.backend.chat_completion(request)
            if not out.choices:
                raise RuntimeError("probe generation returned no choices")
        except BaseException as e:
            with handle.lock:
                handle.probe_failures += 1
            ROUTE_EVENTS.record("route.probe_failures")
            logger.info("replica %s probe failed: %s", handle.replica_id, e)
            return False
        handle.backend.circuit_breaker.record_success()
        handle.rejoin()
        ROUTE_EVENTS.record("route.rejoins")
        logger.info("replica %s passed health probe, rejoining rotation", handle.replica_id)
        return True

    # -- dispatch ----------------------------------------------------------
    def chat_completion(self, request: ChatRequest) -> ChatCompletion:
        """Single-attempt surface (Backend contract): route to the best
        member, no failover/hedging. The reliability entry point is
        ``dispatch_chat_completion``, which this class owns wholesale."""
        handle = self._route()
        return self._attempt(handle, request, hedged=False)

    def dispatch_chat_completion(self, request: ChatRequest) -> ChatCompletion:
        """Route → (hedged) dispatch → failover loop. Replaces the base
        breaker/retry wrapper: each member's own ``dispatch_chat_completion``
        still applies its breaker and retry policy, so wrapping again here
        would double-retry and double-count."""
        if request.seed is None:
            # Pin the seed before the FIRST attempt so any failover replay is
            # byte-identical (the same pinning the supervisor relies on).
            request = dataclasses.replace(
                request, seed=int.from_bytes(os.urandom(4), "little")
            )
        budget = request.budget
        tried: set = set()
        attempts = 0
        shed_errors: List[RateLimitError] = []
        while True:
            if budget is not None:
                budget.check("replica routing")
            try:
                handle = self._route(exclude=frozenset(tried))
            except NoHealthyReplicasError:
                if shed_errors:
                    # Members are healthy-but-full, not down: surface the 429
                    # with retry_after scaled to the capacity actually left.
                    raise self._scaled_rate_limit(shed_errors)
                raise
            ROUTE_EVENTS.record("route.dispatched")
            self._note_member(handle, "routed")
            if attempts > 0:
                FAILOVER_EVENTS.record("failover.attempts")
                with handle.lock:
                    handle.failovers += 1
                self._note_member(handle, "failover")
            try:
                return self._dispatch_hedged(handle, request)
            except RateLimitError as e:
                # Load signal, not a health signal: try another member, and
                # if every member sheds, report aggregate-scaled backpressure.
                shed_errors.append(e)
                tried.add(handle.replica_id)
                if len(tried) >= len(self._handles):
                    raise self._scaled_rate_limit(shed_errors)
                continue
            except _NO_FAILOVER:
                raise
            except BaseException as e:
                handle.mark_down(f"dispatch failed: {type(e).__name__}: {e}")
                FAILOVER_EVENTS.record("failover.member_down")
                ROUTE_EVENTS.record("route.pulled")
                logger.warning(
                    "replica %s failed mid-flight (%s: %s); failing over",
                    handle.replica_id,
                    type(e).__name__,
                    e,
                )
                tried.add(handle.replica_id)
                attempts += 1
                if attempts > self.max_failover_attempts:
                    FAILOVER_EVENTS.record("failover.exhausted")
                    raise

    def _scaled_rate_limit(self, errors: List[RateLimitError]) -> RateLimitError:
        healthy = sum(1 for h in self._handles if h.in_rotation)
        total = len(self._handles)
        base = min(
            (e.retry_after for e in errors if e.retry_after is not None),
            default=1.0,
        )
        scale = total / max(1, healthy)
        return RateLimitError(
            f"all {max(1, healthy)}/{total} healthy replicas at capacity",
            retry_after=min(60.0, base * scale),
        )

    def _attempt(
        self, handle: ReplicaHandle, request: ChatRequest, hedged: bool
    ) -> ChatCompletion:
        """One member attempt. Primary/failover attempts go through the
        member's ``dispatch_chat_completion`` (its breaker + retry policy);
        hedge attempts call the raw ``chat_completion`` so a losing or failing
        hedge never touches a breaker."""
        _failpoints.fire_keyed("replica.dispatch", handle.replica_id)
        t0 = time.perf_counter()
        try:
            if hedged:
                out = handle.backend.chat_completion(request)
            else:
                out = handle.backend.dispatch_chat_completion(request)
        except BaseException:
            handle.note_failure()
            raise
        handle.note_success(time.perf_counter() - t0)
        return out

    def _batch_class(self, handle: ReplicaHandle, request: ChatRequest) -> bool:
        """True when this request's tenant is SLO class ``batch`` on the
        routed member. Batch work never hedges: duplicating it on a second
        member would spend tail-latency capacity on traffic that by contract
        doesn't have a tail SLO. Defaults to interactive on any lookup
        failure (a backend without tenancy hedges as before)."""
        try:
            tenancy = getattr(handle.backend, "tenancy", None)
            if tenancy is None or request.tenant is None:
                return False
            return not tenancy.resolve(request.tenant).interactive
        except Exception:
            return False

    def _hedge_delay(self, handle: ReplicaHandle) -> Optional[float]:
        """Seconds to wait before duplicating on a second member; None
        disables hedging for this dispatch (off, solo set, or no latency
        history yet to define a tail)."""
        if not self.hedge or len(self._handles) < 2:
            return None
        if self.hedge_delay_s is not None:
            return max(0.0, self.hedge_delay_s)
        p95 = handle.p95_s()
        if p95 is None:
            return None
        return max(self.min_hedge_delay_s, p95 * self.hedge_latency_multiplier)

    def _dispatch_hedged(
        self, primary: ReplicaHandle, request: ChatRequest
    ) -> ChatCompletion:
        delay = self._hedge_delay(primary)
        if delay is None or self._batch_class(primary, request):
            return self._attempt(primary, request, hedged=False)

        parent = request.budget

        def child_of(req: ChatRequest) -> Tuple[ChatRequest, RequestBudget]:
            # Each attempt gets its own cancellable budget sharing the
            # parent's deadline, so cancelling the loser aborts ONLY the
            # loser's decode (via the engine's abort poller).
            child = RequestBudget(
                deadline=parent.deadline if parent is not None else None
            )
            return dataclasses.replace(req, budget=child), child

        # in-flight attempts: Future -> (handle, child_budget, kind)
        pending: Dict[Future, Tuple[ReplicaHandle, RequestBudget, str]] = {}
        preq, pbudget = child_of(request)
        pending[self._executor.submit(self._attempt, primary, preq, False)] = (
            primary,
            pbudget,
            "primary",
        )
        hedge_at = time.monotonic() + delay
        hedge_launched = False
        hedged_this_request = False
        errors: Dict[str, BaseException] = {}

        def cancel_all(remaining: Dict[Future, Tuple[ReplicaHandle, RequestBudget, str]]) -> None:
            for fut, (_, child, _) in remaining.items():
                child.cancel()
                fut.add_done_callback(lambda f: f.exception())

        while pending:
            if parent is not None and parent.should_abort():
                cancel_all(pending)
                raise parent.error("replica hedge wait")
            timeout = 0.02
            if not hedge_launched:
                timeout = min(timeout, max(0.0, hedge_at - time.monotonic()))
            done, _ = wait(list(pending), timeout=timeout, return_when=FIRST_COMPLETED)
            if not done:
                if not hedge_launched and time.monotonic() >= hedge_at:
                    hedge_launched = True  # one hedge per dispatch, success or not
                    try:
                        hedge_handle = self._route(
                            exclude=frozenset([primary.replica_id]),
                            advance_round_robin=False,
                        )
                    except NoHealthyReplicasError:
                        continue  # nobody to hedge on; keep waiting on primary
                    hedged_this_request = True
                    HEDGE_EVENTS.record("hedge.launched")
                    self._note_member(hedge_handle, "hedge")
                    hreq, hbudget = child_of(request)
                    pending[
                        self._executor.submit(self._attempt, hedge_handle, hreq, True)
                    ] = (hedge_handle, hbudget, "hedge")
                continue
            for fut in done:
                handle, _, kind = pending.pop(fut)
                exc = fut.exception()
                if exc is None:
                    losers = len(pending)
                    cancel_all(pending)
                    if losers:
                        HEDGE_EVENTS.record("hedge.cancelled_losers", losers)
                    if kind == "hedge":
                        HEDGE_EVENTS.record("hedge.won_hedge")
                        with handle.lock:
                            handle.hedges_won += 1
                        self._note_member(handle, "hedge_won")
                    elif hedged_this_request:
                        HEDGE_EVENTS.record("hedge.won_primary")
                    return fut.result()
                errors[kind] = exc
                if kind == "primary" and isinstance(
                    exc, (RequestTimeoutError, RequestCancelledError)
                ):
                    # The hedge shares the same deadline; don't wait for it
                    # to time out too.
                    cancel_all(pending)
                    raise exc
        # Every attempt failed. The primary's error drives the failover loop
        # (the hedge's failure never reaches a breaker or rotation decision).
        raise errors.get("primary") or next(iter(errors.values()))

    def _note_member(self, handle: ReplicaHandle, kind: str) -> None:
        """Forward route/hedge/failover tallies into the member's scheduler
        stats (TpuBackend members; others have no scheduler and skip)."""
        scheduler = getattr(handle.backend, "scheduler", None)
        if scheduler is None:
            return
        try:
            if kind == "routed":
                scheduler.note_routed()
            elif kind == "failover":
                scheduler.note_failover()
            elif kind == "hedge":
                scheduler.note_hedge()
            elif kind == "hedge_won":
                scheduler.note_hedge(won=True)
        except Exception:  # stats must never fail a dispatch
            logger.debug("replica stats hook failed", exc_info=True)

    # -- non-chat Backend surface (failover, no hedging) -------------------
    def _call_with_failover(self, fn: Callable[[ReplicaHandle], Any]) -> Any:
        tried: set = set()
        attempts = 0
        while True:
            handle = self._route(exclude=frozenset(tried))
            try:
                _failpoints.fire_keyed("replica.dispatch", handle.replica_id)
                return fn(handle)
            except _NO_FAILOVER:
                raise
            except RateLimitError:
                raise
            except BaseException as e:
                handle.note_failure()
                handle.mark_down(f"dispatch failed: {type(e).__name__}: {e}")
                FAILOVER_EVENTS.record("failover.member_down")
                tried.add(handle.replica_id)
                attempts += 1
                if attempts > self.max_failover_attempts:
                    FAILOVER_EVENTS.record("failover.exhausted")
                    raise
                FAILOVER_EVENTS.record("failover.attempts")

    def embeddings(self, texts: List[str]) -> List[List[float]]:
        return self._call_with_failover(lambda h: h.backend.embeddings(texts))

    def embeddings_with_usage(
        self, texts: List[str], model: Optional[str] = None
    ) -> "tuple[List[List[float]], int]":
        return self._call_with_failover(
            lambda h: h.backend.embeddings_with_usage(texts, model=model)
        )

    def crop_texts(
        self, texts: List[str], max_tokens: int, model: Optional[str] = None
    ) -> List[str]:
        for handle in self._handles:
            with handle.lock:
                in_rotation = handle.in_rotation
            if in_rotation:
                return handle.backend.crop_texts(texts, max_tokens, model=model)
        return self._handles[0].backend.crop_texts(texts, max_tokens, model=model)

    def llm_consensus(self, values: List[str]) -> str:
        return self._call_with_failover(lambda h: h.backend.llm_consensus(values))

    @property
    def embedding_model_name(self) -> str:  # type: ignore[override]
        return self._handles[0].backend.embedding_model_name

    @property
    def bills_usage(self) -> bool:  # type: ignore[override]
        return any(h.backend.bills_usage for h in self._handles)

    # -- observability & lifecycle -----------------------------------------
    def _replica_snapshot(self, handle: ReplicaHandle) -> Dict[str, Any]:
        snap = handle.safe_health()
        with handle.lock:
            ewma = handle._ewma_s
            out = {
                "state": str(snap.get("state", "ready"))
                if handle.in_rotation
                else "out_of_rotation",
                "in_rotation": handle.in_rotation,
                "out_reason": handle.out_reason,
                "breaker": str(snap.get("breaker", "closed")),
                "queue_depth": snap.get("queue_depth", 0),
                "in_flight": snap.get("in_flight", 0),
                "dispatched": handle.dispatched,
                "failed": handle.failed,
                "hedges_won": handle.hedges_won,
                "failovers": handle.failovers,
                "probe_failures": handle.probe_failures,
                "ewma_ms": round(ewma * 1000.0, 3) if ewma is not None else None,
            }
        p95 = handle.p95_s()
        out["p95_ms"] = round(p95 * 1000.0, 3) if p95 is not None else None
        return out

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-replica ``{dispatched, failed, hedges_won, ewma_ms, state}``
        (plus rotation detail) keyed by replica id."""
        return {h.replica_id: self._replica_snapshot(h) for h in self._handles}

    def health(self) -> Dict[str, Any]:
        replicas = self.stats()
        healthy = sum(1 for snap in replicas.values() if snap["in_rotation"])
        if healthy == len(replicas):
            state = "ready"
        elif healthy == 0:
            state = "unavailable"
        else:
            state = "degraded"
        return {
            "state": state,
            "breaker": self.circuit_breaker.state,
            "members": len(replicas),
            "healthy_members": healthy,
            "route_policy": self.route_policy,
            "hedge": self.hedge,
            "replicas": replicas,
        }

    def drain(self, timeout: float = 30.0) -> bool:
        self._closed = True
        per_member = timeout / max(1, len(self._handles))
        ok = True
        for handle in self._handles:
            try:
                ok = handle.backend.drain(per_member) and ok
            except BaseException:
                ok = False
        self._executor.shutdown(wait=False)
        return ok

    def close(self) -> None:
        self._closed = True
        for handle in self._handles:
            try:
                handle.backend.close()
            except BaseException:
                logger.debug("replica %s close failed", handle.replica_id, exc_info=True)
        self._executor.shutdown(wait=False)
