"""Request-lifecycle reliability: deadlines, cancellation, retry/backoff,
circuit breaking, and deterministic failpoint injection.

The reference SDK gets all of this from the hosted OpenAI client (``timeout=``
wire contract, SDK retries, server-side shedding); a local TPU engine owns the
whole lifecycle, so this package provides the equivalents and the seams to
test them without real faults.
"""

from . import failpoints
from .deadline import Deadline, RequestBudget
from .failpoints import FailSpec, failpoints as failpoint_scope
from .retry import CircuitBreaker, RetryPolicy, is_retryable
from .supervisor import EngineSupervisor, LaunchBudgetModel
from .tenancy import TenancyConfig, TenantContext, TenantSpec, TokenBucket

__all__ = [
    "CircuitBreaker",
    "Deadline",
    "EngineSupervisor",
    "FailSpec",
    "LaunchBudgetModel",
    "RequestBudget",
    "RetryPolicy",
    "TenancyConfig",
    "TenantContext",
    "TenantSpec",
    "TokenBucket",
    "failpoint_scope",
    "failpoints",
    "is_retryable",
]
