"""Crash-safe write-ahead job store for the offline batch lane (ISSUE 17).

Durability model — the batch lane's exactly-once contract rests on three
mechanisms, each independently recoverable:

1. **Append-only journal** (``journal.log``): every job/item state transition
   is a CRC-framed record (``<len u32><crc32 u32><json payload>``). Commit-
   critical records (job creation, item done/error, requeue checkpoints,
   terminal status) are fsynced before the call returns; cheap advisory
   records (item started) are not — recovery treats a non-committed item as
   pending anyway. A torn tail (partial frame, bad CRC — a kill mid-append)
   is truncated on open and counted (``batch.store_torn_tail``); everything
   before it is intact.

2. **Atomic output segments** (``jobs/<id>/out/<idx>.json``): an item's
   output record is written to a temp file, fsynced, then ``os.replace``d
   into place (+ directory fsync). The rename IS the commit point: a kill at
   any instant leaves either no segment (item re-executes — byte-identical,
   its seed was pinned at submission) or exactly one complete segment. The
   segment is authoritative over the journal: recovery classifies an item by
   its segment when the ``done`` record was lost with the tail.

3. **Assembled output** (``jobs/<id>/output.jsonl``): concatenation of the
   segments in item order, written with the same tmp+fsync+rename dance once
   the job reaches a terminal status. Re-assembly is idempotent.

A duplicate execution (a drain checkpointed an in-flight item back to
``pending`` while its original thread later committed anyway) converges to
one record: both writers target the same segment path with byte-identical
content, so the output file can never hold two records for one item.

The ``batch.store`` failpoint's ``torn`` action fires inside ``_append``:
a prefix of the frame reaches the file, then the append raises — exactly the
disk state a kill mid-write leaves behind, exercisable without a kill.

Retention (ISSUE 18): with ``ttl_s`` set, open runs a one-shot sweep that
GC's terminal jobs older than the TTL — a durable ``gc`` journal record (so
the job can never resurrect from its earlier records), then directory
removal, counted in ``batch.job_swept`` — plus an orphan pass for dirs with
no journal row. Unfinished jobs never expire.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import struct
import time
import zlib
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional

from ..analysis.lockcheck import make_lock
from ..utils.observability import BATCH_EVENTS
from . import failpoints as _failpoints

logger = logging.getLogger(__name__)

__all__ = ["JobStore", "JobState", "TERMINAL_STATUSES", "ITEM_STATES"]

#: Job statuses a job can never leave; output.jsonl exists once reached.
TERMINAL_STATUSES = ("completed", "completed_with_errors", "cancelled")

#: Per-item lifecycle. ``started`` is advisory (un-fsynced): recovery demotes
#: it back to ``pending`` unless a committed segment proves completion.
ITEM_STATES = ("pending", "started", "done", "error")

_FRAME = struct.Struct("<II")  # (payload length, crc32(payload))


def _fsync_dir(path: Path) -> None:
    # Durable rename: the directory entry itself must reach the platter.
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover - exotic filesystems
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_atomic(path: Path, data: bytes, fsync: bool = True) -> None:
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        if fsync:
            os.fsync(fh.fileno())
    os.replace(tmp, path)
    if fsync:
        _fsync_dir(path.parent)


@dataclass
class JobState:
    """In-memory job row, rebuilt from the journal + segments on open."""

    id: str
    tenant: str
    n_items: int
    created_at: float
    status: str = "queued"  # queued | in_progress | <TERMINAL_STATUSES>
    cancelled: bool = False
    items: List[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.items:
            self.items = ["pending"] * self.n_items

    @property
    def terminal(self) -> bool:
        return self.status in TERMINAL_STATUSES

    def counts(self) -> Dict[str, int]:
        return {
            "total": self.n_items,
            "completed": sum(1 for s in self.items if s == "done"),
            "failed": sum(1 for s in self.items if s == "error"),
        }

    def snapshot(self) -> "JobState":
        return JobState(
            id=self.id, tenant=self.tenant, n_items=self.n_items,
            created_at=self.created_at, status=self.status,
            cancelled=self.cancelled, items=list(self.items),
        )


class JobStore:
    """One directory of durable batch jobs behind one leaf lock.

    Layout::

        <root>/journal.log              CRC-framed state transitions
        <root>/jobs/<id>/input.jsonl    normalized items (seeds pinned)
        <root>/jobs/<id>/out/00007.json committed output segment for item 7
        <root>/jobs/<id>/output.jsonl   assembled once the job is terminal
    """

    def __init__(
        self, root: Any, *, fsync: bool = True, ttl_s: Optional[float] = None
    ) -> None:
        self.root = Path(root)
        self._fsync_enabled = fsync
        self.ttl_s = float(ttl_s) if ttl_s else 0.0
        # Leaf lock: guards the job table and journal appends; never held
        # across a model call (the lane executes items outside it).
        self._lock = make_lock("reliability.jobstore")
        self._jobs: Dict[str, JobState] = {}
        self._jobs_dir = self.root / "jobs"
        self._jobs_dir.mkdir(parents=True, exist_ok=True)
        self._journal_path = self.root / "journal.log"
        self._recover()
        self._fh = open(self._journal_path, "ab")
        if self.ttl_s > 0:
            with self._lock:
                self._sweep_expired_locked()

    # -- journal framing ---------------------------------------------------
    def _append(self, payload: Dict[str, Any], sync: bool) -> None:
        data = json.dumps(payload, separators=(",", ":")).encode()
        frame = _FRAME.pack(len(data), zlib.crc32(data)) + data
        spec = _failpoints.fire("batch.store")
        if spec is not None and getattr(spec, "action", None) == "torn":
            # Simulated kill mid-append: a prefix of the frame reaches the
            # file, the writer is gone. Recovery must truncate this tail.
            self._fh.write(frame[: max(1, len(frame) // 2)])
            self._fh.flush()
            raise RuntimeError(
                "injected torn journal append (failpoint): batch.store "
                "record truncated mid-write"
            )
        self._fh.write(frame)
        self._fh.flush()
        if sync and self._fsync_enabled:
            os.fsync(self._fh.fileno())

    def _read_journal(self) -> List[Dict[str, Any]]:
        """Replay every intact record; truncate a torn tail in place."""
        records: List[Dict[str, Any]] = []
        if not self._journal_path.exists():
            return records
        raw = self._journal_path.read_bytes()
        offset = 0
        good = 0
        while offset + _FRAME.size <= len(raw):
            length, crc = _FRAME.unpack_from(raw, offset)
            start = offset + _FRAME.size
            end = start + length
            if end > len(raw):
                break  # partial payload: torn tail
            payload = raw[start:end]
            if zlib.crc32(payload) != crc:
                break  # corrupt frame: everything after is untrusted
            try:
                records.append(json.loads(payload))
            except ValueError:
                break
            offset = end
            good = end
        if good < len(raw):
            BATCH_EVENTS.record("batch.store_torn_tail")
            logger.warning(
                "jobstore: truncating torn journal tail (%d of %d bytes kept)",
                good, len(raw),
            )
            with open(self._journal_path, "ab") as fh:
                fh.truncate(good)
        return records

    # -- recovery ----------------------------------------------------------
    def _recover(self) -> None:
        # Only ever called from __init__ (no concurrent readers yet); the
        # lock is held anyway so the guarded-by invariant on _jobs is total.
        with self._lock:
            self._recover_locked()

    def _recover_locked(self) -> None:
        for rec in self._read_journal():
            kind = rec.get("t")
            if kind == "job":
                self._jobs[rec["id"]] = JobState(
                    id=rec["id"], tenant=rec.get("tenant", "default"),
                    n_items=int(rec["n"]),
                    created_at=float(rec.get("created_at", 0.0)),
                )
            elif kind == "item":
                job = self._jobs.get(rec.get("id"))
                idx = int(rec.get("idx", -1))
                if job is not None and 0 <= idx < job.n_items:
                    job.items[idx] = rec.get("s", "pending")
                    if job.status == "queued" and rec.get("s") == "started":
                        job.status = "in_progress"
            elif kind == "status":
                job = self._jobs.get(rec.get("id"))
                if job is not None:
                    job.status = rec.get("s", job.status)
                    if job.status == "cancelled":
                        job.cancelled = True
            elif kind == "gc":
                # Swept by a TTL pass: the job must NOT resurrect — without
                # this record, replaying its "job" record against a deleted
                # directory would revive it as a cancelled ghost (_reconcile
                # sees no input.jsonl).
                self._jobs.pop(rec.get("id"), None)
        for job in self._jobs.values():
            self._reconcile(job)

    def _reconcile(self, job: JobState) -> None:
        """Disk is authoritative: segments decide done/error; ``started``
        without a segment rolls back to ``pending``; ``*.tmp`` leftovers
        (a kill between write and rename) are discarded."""
        jobdir = self._jobs_dir / job.id
        outdir = jobdir / "out"
        for stray in glob.glob(str(outdir / "*.tmp")):
            os.unlink(stray)
        committed: Dict[int, bool] = {}
        for seg in glob.glob(str(outdir / "*.json")):
            try:
                idx = int(Path(seg).stem)
                record = json.loads(Path(seg).read_bytes())
                committed[idx] = record.get("error") is not None
            except (ValueError, OSError):
                # Can't happen under the fsync-before-rename model; if the
                # platter lied, re-execution is the safe direction.
                os.unlink(seg)
        for idx in range(job.n_items):
            if idx in committed:
                job.items[idx] = "error" if committed[idx] else "done"
            elif job.items[idx] == "started":
                job.items[idx] = "pending"
                BATCH_EVENTS.record("batch.item_requeued")
        if not (jobdir / "input.jsonl").exists():
            logger.warning(
                "jobstore: job %s has no input.jsonl (killed mid-create); "
                "marking cancelled", job.id,
            )
            job.status = "cancelled"
            job.cancelled = True
            return
        if not job.terminal and all(s in ("done", "error") for s in job.items):
            job.status = (
                "completed_with_errors"
                if any(s == "error" for s in job.items) else "completed"
            )
        if job.terminal and not (jobdir / "output.jsonl").exists():
            self._assemble(job)

    # -- TTL sweep (ISSUE 18) ----------------------------------------------
    def _sweep_expired_locked(self) -> None:
        """GC terminal jobs older than ``ttl_s`` (age from submission — the
        only timestamp the journal carries). Runs once per open, before any
        concurrent writers exist. Order per job: durable ``gc`` journal
        record first, then directory removal — a kill between the two leaves
        a dir the orphan pass below deletes on the next open. Non-terminal
        jobs never expire (the lane still owes them execution)."""
        import shutil

        now = time.time()
        for jid in list(self._jobs):
            job = self._jobs[jid]
            if not job.terminal or now - job.created_at <= self.ttl_s:
                continue
            self._append({"t": "gc", "id": jid}, sync=True)
            del self._jobs[jid]
            shutil.rmtree(self._jobs_dir / jid, ignore_errors=True)
            BATCH_EVENTS.record("batch.job_swept")
            logger.info(
                "jobstore: swept expired job %s (age %.0fs > ttl %.0fs)",
                jid, now - job.created_at, self.ttl_s,
            )
        # Orphan pass: directories with no live job row — an interrupted
        # rmtree above, or a create killed before its journal record.
        for path in self._jobs_dir.iterdir():
            if path.is_dir() and path.name not in self._jobs:
                shutil.rmtree(path, ignore_errors=True)

    # -- job lifecycle -----------------------------------------------------
    def create_job(
        self,
        items: List[Dict[str, Any]],
        tenant: str,
        job_id: Optional[str] = None,
    ) -> JobState:
        jid = job_id or "batch_" + os.urandom(12).hex()
        jobdir = self._jobs_dir / jid
        (jobdir / "out").mkdir(parents=True, exist_ok=True)
        lines = b"".join(
            json.dumps(item, separators=(",", ":")).encode() + b"\n"
            for item in items
        )
        # Input before journal: a journal job record always has its items.
        _write_atomic(jobdir / "input.jsonl", lines, fsync=self._fsync_enabled)
        job = JobState(
            id=jid, tenant=tenant, n_items=len(items), created_at=time.time()
        )
        with self._lock:
            self._append(
                {
                    "t": "job", "id": jid, "tenant": tenant,
                    "n": job.n_items, "created_at": job.created_at,
                },
                sync=True,
            )
            self._jobs[jid] = job
        return job.snapshot()

    def load_items(self, job_id: str) -> List[Dict[str, Any]]:
        path = self._jobs_dir / job_id / "input.jsonl"
        return [
            json.loads(line)
            for line in path.read_bytes().splitlines() if line.strip()
        ]

    def note_item_started(self, job_id: str, idx: int) -> bool:
        """Advisory (un-fsynced): marks intent, never durability."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.cancelled or job.items[idx] != "pending":
                return False
            job.items[idx] = "started"
            if job.status == "queued":
                job.status = "in_progress"
            self._append(
                {"t": "item", "id": job_id, "idx": idx, "s": "started"},
                sync=False,
            )
            return True

    def commit_item(
        self, job_id: str, idx: int, record: Dict[str, Any],
        error: bool = False,
    ) -> bool:
        """The exactly-once commit: segment rename, then a durable journal
        record. Idempotent — a duplicate execution rewrites the same segment
        with the same bytes."""
        outdir = self._jobs_dir / job_id / "out"
        line = json.dumps(record, separators=(",", ":")).encode() + b"\n"
        _write_atomic(
            outdir / f"{idx:05d}.json", line, fsync=self._fsync_enabled
        )
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return False
            state = "error" if error else "done"
            already = job.items[idx] == state
            job.items[idx] = state
            if not already:
                self._append(
                    {"t": "item", "id": job_id, "idx": idx, "s": state},
                    sync=True,
                )
            return True

    def requeue_item(self, job_id: str, idx: int) -> bool:
        """Checkpoint an in-flight item back to pending (drain/crash). A
        durable record: after restart the item re-executes from scratch."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.items[idx] != "started":
                return False
            job.items[idx] = "pending"
            self._append(
                {"t": "item", "id": job_id, "idx": idx, "s": "pending"},
                sync=True,
            )
            return True

    def finish_job(self, job_id: str) -> Optional[str]:
        """Terminalize once every item is done/error; assembles the output."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.terminal:
                return job.status if job else None
            if not all(s in ("done", "error") for s in job.items):
                return None
            job.status = (
                "completed_with_errors"
                if any(s == "error" for s in job.items) else "completed"
            )
            self._append(
                {"t": "status", "id": job_id, "s": job.status}, sync=True
            )
            self._assemble(job)
            return job.status

    def cancel_job(self, job_id: str) -> Optional[str]:
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            if job.terminal:
                return job.status
            job.cancelled = True
            job.status = "cancelled"
            self._append(
                {"t": "status", "id": job_id, "s": "cancelled"}, sync=True
            )
            self._assemble(job)
            return job.status

    def _assemble(self, job: JobState) -> None:
        """Concatenate committed segments (item order) into output.jsonl."""
        jobdir = self._jobs_dir / job.id
        chunks: List[bytes] = []
        for idx in range(job.n_items):
            seg = jobdir / "out" / f"{idx:05d}.json"
            if seg.exists():
                chunks.append(seg.read_bytes())
        _write_atomic(
            jobdir / "output.jsonl", b"".join(chunks),
            fsync=self._fsync_enabled,
        )

    # -- reads -------------------------------------------------------------
    def job(self, job_id: str) -> Optional[JobState]:
        with self._lock:
            job = self._jobs.get(job_id)
            return job.snapshot() if job is not None else None

    def jobs(self) -> Dict[str, JobState]:
        with self._lock:
            return {jid: job.snapshot() for jid, job in self._jobs.items()}

    def unfinished_jobs(self) -> List[JobState]:
        with self._lock:
            return [
                job.snapshot()
                for job in self._jobs.values() if not job.terminal
            ]

    def read_output(self, job_id: str) -> Optional[bytes]:
        """Assembled output bytes for a terminal job; None otherwise."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or not job.terminal:
                return None
        path = self._jobs_dir / job_id / "output.jsonl"
        if not path.exists():
            with self._lock:
                self._assemble(self._jobs[job_id])
        return path.read_bytes()

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:  # pragma: no cover
            pass
