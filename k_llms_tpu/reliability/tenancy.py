"""Multi-tenant quotas, SLO classes, and weighted-fair shares (ISSUE 16).

The serving stack already has budgets (PR 1), priority queuing (PR 2), and
per-request traces/histograms (PR 14), but nothing composing them into
*tenancy*: one bulk-extraction customer can starve interactive chat and no
scrape output can prove otherwise. This module supplies the policy objects
the admission path needs:

- :class:`TokenBucket` — a monotonic-clock token bucket with ``try_take``
  (atomic under the owner's lock) and ``time_until`` (the tenant's own
  refill horizon, which becomes the 429 ``retry_after`` instead of the
  global drain-rate estimate).
- :class:`TenantSpec` — frozen per-tenant policy: WFQ ``weight``, SLO class
  (``interactive`` | ``batch``), and optional request/s + device-row/s
  quotas (None = unlimited).
- :class:`TenantContext` — a spec plus its two live buckets behind one
  lock. ``try_admit(rows)`` checks BOTH buckets before deducting either,
  so a partial charge can never leak tokens on a rejected request.
- :class:`TenancyConfig` — the registry: a default spec, named overrides,
  an API-key → tenant-name map for ``serving/app.py`` resolution, and a
  bounded cache of dynamically materialized contexts (unmapped API keys
  become their own tenants so per-key fairness works without pre-config).

Scheduling policy built on these lives in ``engine/scheduler.py`` (WFQ over
coalesced launches, brownout shed tiers) and ``engine/continuous.py`` (WFQ
slot admission); this module is pure bookkeeping with no thread of its own.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, Mapping, Optional, Tuple

from ..analysis.lockcheck import make_lock

__all__ = [
    "SLO_CLASSES",
    "TokenBucket",
    "TenantSpec",
    "TenantContext",
    "TenancyConfig",
    "DEFAULT_TENANT",
    "BATCH_LANE_SUFFIX",
]

#: Recognized SLO classes, in strictly descending admission priority.
SLO_CLASSES: Tuple[str, ...] = ("interactive", "batch")

#: Name of the implicit tenant used when no credential resolves.
DEFAULT_TENANT = "default"

#: Dynamic (API-key-derived) tenant contexts are capped; overflow collapses
#: to the default tenant so a credential-spraying client cannot grow the
#: registry (or the /metrics label set) without bound.
MAX_DYNAMIC_TENANTS = 1024

#: Name suffix of a tenant's derived batch-lane context (ISSUE 17). ``#`` can
#: never appear in an API-key-derived tenant name's configured form by
#: accident of quoting — and even if a hostile key contains it, the lane view
#: only ever SHARES the owner's buckets, so no quota is gained by collision.
BATCH_LANE_SUFFIX = "#batch"


class TokenBucket:
    """Classic token bucket over a monotonic clock.

    Not internally locked — the owning :class:`TenantContext` serializes
    access so its two buckets (requests/s and rows/s) charge atomically.
    """

    __slots__ = ("rate", "burst", "_level", "_stamp", "_clock")

    def __init__(
        self,
        rate: float,
        burst: float,
        clock: Callable[[], float] = time.monotonic,
    ):
        if rate <= 0:
            raise ValueError(f"token bucket rate must be > 0, got {rate}")
        if burst <= 0:
            raise ValueError(f"token bucket burst must be > 0, got {burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._level = float(burst)
        self._stamp = clock()
        self._clock = clock

    def _refill(self) -> None:
        now = self._clock()
        elapsed = now - self._stamp
        if elapsed > 0:
            self._level = min(self.burst, self._level + elapsed * self.rate)
        self._stamp = now

    def try_take(self, cost: float = 1.0) -> bool:
        """Deduct ``cost`` tokens if available; False leaves the level as-is."""
        self._refill()
        if self._level >= cost:
            self._level -= cost
            return True
        return False

    def time_until(self, cost: float = 1.0) -> float:
        """Seconds until ``cost`` tokens will be available (0.0 if now).

        Costs beyond ``burst`` can never be satisfied; report the full-burst
        refill horizon so callers still get a finite, honest retry hint.
        """
        self._refill()
        deficit = min(cost, self.burst) - self._level
        if deficit <= 0:
            return 0.0
        return deficit / self.rate

    def level(self) -> float:
        """Current token level (refills first); diagnostic only."""
        self._refill()
        return self._level


@dataclass(frozen=True)
class TenantSpec:
    """Frozen per-tenant policy. ``None`` quota fields mean unlimited."""

    name: str
    weight: float = 1.0
    slo: str = "interactive"
    requests_per_s: Optional[float] = None
    request_burst: Optional[float] = None
    rows_per_s: Optional[float] = None
    rows_burst: Optional[float] = None

    def __post_init__(self):
        if not self.name:
            raise ValueError("tenant name must be non-empty")
        if self.slo not in SLO_CLASSES:
            raise ValueError(
                f"tenant {self.name!r}: slo must be one of {SLO_CLASSES}, "
                f"got {self.slo!r}"
            )
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0, got {self.weight}"
            )
        for fname in ("requests_per_s", "request_burst", "rows_per_s", "rows_burst"):
            v = getattr(self, fname)
            if v is not None and v <= 0:
                raise ValueError(
                    f"tenant {self.name!r}: {fname} must be > 0 or None, got {v}"
                )


class TenantContext:
    """A :class:`TenantSpec` plus live quota state.

    One lock guards both buckets so a request's (1 request, N rows) charge is
    atomic: either both buckets admit and both are deducted, or neither is
    touched and the caller gets the max of the two refill horizons.
    """

    __slots__ = ("spec", "_lock", "_req_bucket", "_row_bucket")

    def __init__(
        self, spec: TenantSpec, clock: Callable[[], float] = time.monotonic
    ):
        self.spec = spec
        # Leaf lock: taken under the scheduler's condition (quota checks in
        # eviction tiers) and never the other way around.
        self._lock = make_lock("tenancy.tenant")
        self._req_bucket: Optional[TokenBucket] = None
        self._row_bucket: Optional[TokenBucket] = None
        if spec.requests_per_s is not None:
            burst = spec.request_burst
            if burst is None:
                burst = max(1.0, spec.requests_per_s)
            self._req_bucket = TokenBucket(spec.requests_per_s, burst, clock)
        if spec.rows_per_s is not None:
            burst = spec.rows_burst
            if burst is None:
                burst = max(1.0, spec.rows_per_s)
            self._row_bucket = TokenBucket(spec.rows_per_s, burst, clock)

    # -- identity passthroughs -------------------------------------------
    @property
    def name(self) -> str:
        return self.spec.name

    @property
    def weight(self) -> float:
        return self.spec.weight

    @property
    def slo(self) -> str:
        return self.spec.slo

    @property
    def interactive(self) -> bool:
        return self.spec.slo == "interactive"

    @property
    def limited(self) -> bool:
        return self._req_bucket is not None or self._row_bucket is not None

    # -- quota -----------------------------------------------------------
    def try_admit(self, rows: float = 0.0) -> Optional[float]:
        """Charge one request + ``rows`` device rows against the quotas.

        Returns ``None`` on success (both buckets deducted atomically) or
        the number of seconds until this tenant's OWN buckets could admit
        the same charge — the quota-aware ``retry_after``.
        """
        with self._lock:
            wait = 0.0
            if self._req_bucket is not None:
                wait = max(wait, self._req_bucket.time_until(1.0))
            if self._row_bucket is not None and rows > 0:
                wait = max(wait, self._row_bucket.time_until(rows))
            if wait > 0:
                return wait
            if self._req_bucket is not None:
                self._req_bucket.try_take(1.0)
            if self._row_bucket is not None and rows > 0:
                self._row_bucket.try_take(rows)
            return None

    def refill_horizon(self, rows: float = 0.0) -> float:
        """Seconds until the buckets could admit one request + ``rows`` rows,
        WITHOUT charging anything. 0.0 when admissible now (or unlimited) —
        the scheduler uses this for forced quota misses (the
        ``scheduler.tenant=exhaust`` failpoint) and brownout retry hints."""
        with self._lock:
            wait = 0.0
            if self._req_bucket is not None:
                wait = max(wait, self._req_bucket.time_until(1.0))
            if self._row_bucket is not None and rows > 0:
                wait = max(wait, self._row_bucket.time_until(rows))
            return wait

    def over_quota(self) -> bool:
        """True when either bucket is currently empty — used by brownout
        eviction to pick over-quota interactive victims before in-SLO work."""
        with self._lock:
            if self._req_bucket is not None and self._req_bucket.level() < 1.0:
                return True
            if self._row_bucket is not None and self._row_bucket.level() < 1.0:
                return True
            return False

    def quota_snapshot(self) -> Dict[str, Any]:
        """Bucket levels for health/debug endpoints."""
        with self._lock:
            snap: Dict[str, Any] = {"slo": self.spec.slo, "weight": self.spec.weight}
            if self._req_bucket is not None:
                snap["request_tokens"] = round(self._req_bucket.level(), 3)
            if self._row_bucket is not None:
                snap["row_tokens"] = round(self._row_bucket.level(), 3)
            return snap

    @classmethod
    def lane_view(cls, owner: "TenantContext", spec: TenantSpec) -> "TenantContext":
        """A sibling context over the OWNER'S lock and buckets (ISSUE 17).

        The offline batch lane runs under the owning tenant's quota but the
        ``batch`` SLO class, and the scheduler keys its WFQ queues by context
        name — so the lane needs a distinct name and spec while every quota
        charge still lands atomically in the owner's token buckets."""
        view = cls.__new__(cls)
        view.spec = spec
        view._lock = owner._lock
        view._req_bucket = owner._req_bucket
        view._row_bucket = owner._row_bucket
        return view

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TenantContext({self.spec.name!r}, slo={self.spec.slo!r})"


@dataclass
class TenancyConfig:
    """The tenant registry the admission path consults.

    ``default`` covers unconfigured traffic; ``tenants`` holds named
    overrides; ``api_keys`` maps serving-layer credentials to tenant names.
    Unmapped API keys materialize their own (default-policy) contexts so
    per-key fairness and per-key metrics work without pre-registration —
    bounded by :data:`MAX_DYNAMIC_TENANTS`.
    """

    default: TenantSpec = field(
        default_factory=lambda: TenantSpec(name=DEFAULT_TENANT)
    )
    tenants: Dict[str, TenantSpec] = field(default_factory=dict)
    api_keys: Dict[str, str] = field(default_factory=dict)
    clock: Callable[[], float] = time.monotonic

    def __post_init__(self):
        self._lock = make_lock("tenancy.registry")
        self._contexts: Dict[str, TenantContext] = {}
        for name, spec in self.tenants.items():
            if spec.name != name:
                raise ValueError(
                    f"tenant registry key {name!r} != spec.name {spec.name!r}"
                )
        self._dynamic = 0

    @classmethod
    def from_options(
        cls,
        *,
        default_weight: float = 1.0,
        default_slo: str = "interactive",
        default_requests_per_s: Optional[float] = None,
        default_rows_per_s: Optional[float] = None,
        tenants: Optional[Mapping[str, Mapping[str, Any]]] = None,
        api_keys: Optional[Mapping[str, str]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> "TenancyConfig":
        """Build from the flat knob shapes ``BackendConfig`` carries.

        ``tenants`` values are dicts of TenantSpec field overrides, e.g.
        ``{"bulk": {"slo": "batch", "weight": 1.0, "rows_per_s": 8}}``.
        """
        default = TenantSpec(
            name=DEFAULT_TENANT,
            weight=default_weight,
            slo=default_slo,
            requests_per_s=default_requests_per_s,
            rows_per_s=default_rows_per_s,
        )
        specs: Dict[str, TenantSpec] = {}
        for name, overrides in dict(tenants or {}).items():
            fields = {
                "weight": default.weight,
                "slo": default.slo,
                "requests_per_s": default.requests_per_s,
                "rows_per_s": default.rows_per_s,
            }
            fields.update(dict(overrides))
            fields.pop("name", None)
            specs[name] = TenantSpec(name=name, **fields)
        return cls(
            default=default, tenants=specs, api_keys=dict(api_keys or {}),
            clock=clock,
        )

    # -- resolution ------------------------------------------------------
    def resolve(self, tenant: Any = None) -> TenantContext:
        """Resolve a request's ``tenant=`` value to a live context.

        ``None`` → the default tenant; a :class:`TenantContext` passes
        through; a string names a configured tenant or materializes a
        dynamic one (default policy, own buckets) up to the cap.
        """
        if tenant is None:
            return self._context(self.default.name, self.default)
        if isinstance(tenant, TenantContext):
            return tenant
        name = str(tenant)
        with self._lock:
            ctx = self._contexts.get(name)
        if ctx is not None:
            return ctx
        if name.endswith(BATCH_LANE_SUFFIX):
            # A lane name round-tripped as a string (Completions.create's
            # tenant= is a plain str): re-derive the shared-bucket view
            # instead of materializing an unrelated dynamic tenant.
            return self.batch_lane(name[: -len(BATCH_LANE_SUFFIX)] or None)
        spec = self.tenants.get(name)
        if spec is not None:
            return self._context(name, spec)
        if name == self.default.name:
            return self._context(name, self.default)
        # Dynamic tenant: default policy under its own name (own buckets).
        with self._lock:
            if self._dynamic >= MAX_DYNAMIC_TENANTS:
                name = self.default.name
                spec = self.default
            else:
                self._dynamic += 1
                spec = TenantSpec(
                    name=name,
                    weight=self.default.weight,
                    slo=self.default.slo,
                    requests_per_s=self.default.requests_per_s,
                    rows_per_s=self.default.rows_per_s,
                )
        return self._context(name, spec)

    def tenant_for_key(self, api_key: Optional[str]) -> str:
        """Map a serving-layer credential to a tenant name.

        Mapped keys get their configured tenant; unmapped non-empty keys
        become their own dynamic tenant (per-key fairness by default);
        missing/empty credentials fall to the default tenant.
        """
        if not api_key:
            return self.default.name
        mapped = self.api_keys.get(api_key)
        if mapped is not None:
            return mapped
        return api_key

    def _context(self, name: str, spec: TenantSpec) -> TenantContext:
        with self._lock:
            ctx = self._contexts.get(name)
            if ctx is None:
                ctx = TenantContext(spec, clock=self.clock)
                self._contexts[name] = ctx
            return ctx

    def batch_lane(self, tenant: Any = None) -> TenantContext:
        """The batch-SLO sibling of a tenant: ``<name>#batch`` (ISSUE 17).

        Shares the owner's lock and token buckets (offline work draws down
        the SAME quota as the owner's interactive traffic) but carries
        ``slo="batch"`` under its own name, so the scheduler's WFQ keys it
        as a separate, strictly-lower-priority queue. A tenant already in
        the batch class IS its own lane."""
        owner = self.resolve(tenant)
        if owner.slo == "batch":
            return owner
        lane_name = owner.name + BATCH_LANE_SUFFIX
        with self._lock:
            ctx = self._contexts.get(lane_name)
            if ctx is None:
                spec = replace(owner.spec, name=lane_name, slo="batch")
                ctx = TenantContext.lane_view(owner, spec)
                self._contexts[lane_name] = ctx
            return ctx

    def known_tenants(self) -> Dict[str, TenantContext]:
        """Snapshot of materialized contexts (for health endpoints)."""
        with self._lock:
            return dict(self._contexts)


def permissive() -> TenancyConfig:
    """An unlimited single-class config — the implicit policy everywhere a
    component is constructed without explicit tenancy, preserving pre-tenancy
    behavior bit-for-bit (no quotas, one weight, everything interactive)."""
    return TenancyConfig()
