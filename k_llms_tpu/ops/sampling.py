"""On-device token sampling with logprob capture.

The n consensus samples are one batched categorical draw: per-sample RNG keys
(folded from the request seed) make the samples diverse yet reproducible —
covering the reference's `seed` pass-through
(`/root/reference/k_llms/resources/completions/completions.py:57-58`) that the
OpenAI backend only best-effort honors. The logprob of every emitted token is
captured from the UNtempered distribution (that is what OpenAI's `logprobs`
reports) and feeds the likelihood-weighted consensus mode.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def sample_logits(
    logits: jax.Array,
    key: Optional[jax.Array],
    temperature: float = 1.0,
    top_p: Optional[float] = None,
    top_k: Optional[int] = None,
    row_keys: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Sample next tokens. logits: [B, V] f32; key: one PRNG key, folded per row.
    ``row_keys`` ([B] typed keys) overrides the internal per-row fold — the
    coalesced multi-request decode path derives each row's key from its OWN
    request seed so per-request draws don't depend on batch composition.

    Returns (tokens [B] int32, logprobs [B] f32 — log p(token) under the
    untempered model distribution).
    """
    B, V = logits.shape
    # Failure tolerance: a sample whose logits went non-finite (overflow in a
    # bad checkpoint, etc.) must not poison the batch — sanitize to a uniform
    # distribution for that row; the consensus layer then simply outvotes it.
    finite = jnp.isfinite(logits)
    row_ok = jnp.any(finite, axis=-1, keepdims=True)
    logits = jnp.where(finite, logits, -jnp.inf)
    logits = jnp.where(row_ok, logits, 0.0)
    model_logprobs = jax.nn.log_softmax(logits, axis=-1)

    if temperature == 0.0:
        tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        sampling_logits = logits / temperature

        if top_k is not None and top_k < V:
            kth = jnp.sort(sampling_logits, axis=-1)[:, V - top_k][:, None]
            sampling_logits = jnp.where(sampling_logits < kth, -jnp.inf, sampling_logits)

        if top_p is not None and top_p < 1.0:
            sorted_logits = jnp.sort(sampling_logits, axis=-1)[:, ::-1]
            sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
            cumulative = jnp.cumsum(sorted_probs, axis=-1)
            # Keep the smallest prefix with cumulative mass >= top_p (the token
            # that crosses the boundary stays in).
            keep_sorted = (cumulative - sorted_probs) < top_p
            threshold = jnp.min(
                jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
            )
            sampling_logits = jnp.where(sampling_logits < threshold, -jnp.inf, sampling_logits)

        if row_keys is None:
            keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(key, jnp.arange(B))
        else:
            keys = row_keys
        tokens = jax.vmap(lambda k, l: jax.random.categorical(k, l))(keys, sampling_logits)
        tokens = tokens.astype(jnp.int32)

    logprobs = jnp.take_along_axis(model_logprobs, tokens[:, None], axis=-1)[:, 0]
    return tokens, logprobs
