"""On-device token sampling with logprob capture.

The n consensus samples are one batched categorical draw: per-sample RNG keys
(folded from the request seed) make the samples diverse yet reproducible —
covering the reference's `seed` pass-through
(`/root/reference/k_llms/resources/completions/completions.py:57-58`) that the
OpenAI backend only best-effort honors. The logprob of every emitted token is
captured from the UNtempered distribution (that is what OpenAI's `logprobs`
reports) and feeds the likelihood-weighted consensus mode.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def sample_logits(
    logits: jax.Array,
    key: Optional[jax.Array],
    temperature: float = 1.0,
    top_p: Optional[float] = None,
    top_k: Optional[int] = None,
    row_keys: Optional[jax.Array] = None,
    penalty: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Sample next tokens. logits: [B, V] f32; key: one PRNG key, folded per row.
    ``row_keys`` ([B] typed keys) overrides the internal per-row fold — the
    coalesced multi-request decode path derives each row's key from its OWN
    request seed so per-request draws don't depend on batch composition.
    ``penalty`` ([B, V] f32) is subtracted from the logits BEFORE temperature
    (OpenAI's frequency/presence formula: mu[j] - c[j]*a_freq - 1{c}*a_pres);
    it shapes the sampling distribution only — reported logprobs stay the
    unpenalized model distribution's.

    Returns (tokens [B] int32, logprobs [B] f32 — log p(token) under the
    untempered model distribution).
    """
    B, V = logits.shape
    # Failure tolerance: a sample whose logits went non-finite (overflow in a
    # bad checkpoint, etc.) must not poison the batch — sanitize to a uniform
    # distribution for that row; the consensus layer then simply outvotes it.
    finite = jnp.isfinite(logits)
    row_ok = jnp.any(finite, axis=-1, keepdims=True)
    logits = jnp.where(finite, logits, -jnp.inf)
    logits = jnp.where(row_ok, logits, 0.0)
    model_logprobs = jax.nn.log_softmax(logits, axis=-1)
    if penalty is not None:
        logits = logits - penalty

    if temperature == 0.0:
        tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        sampling_logits = logits / temperature

        if top_k is not None and top_k < V:
            kth = jnp.sort(sampling_logits, axis=-1)[:, V - top_k][:, None]
            sampling_logits = jnp.where(sampling_logits < kth, -jnp.inf, sampling_logits)

        if top_p is not None and top_p < 1.0:
            # Keep the smallest set with cumulative mass >= top_p (boundary
            # token stays in; equal-logit ties stay in). Implemented as a
            # bisection on the logit threshold instead of a full-vocab sort:
            # mass({logit > t}) is monotone in t, and the loop runs until
            # every row's bracket has collapsed to ADJACENT floats (midpoint
            # rounds onto an endpoint — a stalled row no longer changes), at
            # which point no representable logit lies strictly inside it and
            # the kept set {logit > lo} is EXACTLY the sort-based set — at a
            # fraction of the cost (XLA's 128k-wide sort is ~5.5 ms/step for
            # n=32 on v5e; this is typically ~30 masked reductions).
            probs = jax.nn.softmax(sampling_logits, axis=-1)
            finite = jnp.isfinite(sampling_logits)
            lo = (
                jnp.min(jnp.where(finite, sampling_logits, jnp.inf), axis=-1) - 1.0
            )  # below every value: mass({> lo}) = 1 >= top_p
            hi = jnp.max(
                jnp.where(finite, sampling_logits, -jnp.inf), axis=-1
            )  # the max value: mass({> hi}) = 0 < top_p

            def _progress(lohi):
                lo, hi = lohi
                mid = 0.5 * (lo + hi)
                return jnp.any((mid > lo) & (mid < hi))

            def _bisect(lohi):
                lo, hi = lohi
                mid = 0.5 * (lo + hi)
                mass = jnp.sum(
                    jnp.where(sampling_logits > mid[:, None], probs, 0.0), axis=-1
                )
                go_hi = mass < top_p
                return jnp.where(go_hi, lo, mid), jnp.where(go_hi, mid, hi)

            lo, hi = jax.lax.while_loop(_progress, _bisect, (lo, hi))
            # The boundary token's logit: smallest present value above lo.
            threshold = jnp.min(
                jnp.where(sampling_logits > lo[:, None], sampling_logits, jnp.inf),
                axis=-1,
                keepdims=True,
            )
            sampling_logits = jnp.where(sampling_logits < threshold, -jnp.inf, sampling_logits)

        if row_keys is None:
            keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(key, jnp.arange(B))
        else:
            keys = row_keys
        tokens = jax.vmap(lambda k, l: jax.random.categorical(k, l))(keys, sampling_logits)
        tokens = tokens.astype(jnp.int32)

    logprobs = jnp.take_along_axis(model_logprobs, tokens[:, None], axis=-1)[:, 0]
    return tokens, logprobs


def model_top_logprobs(
    logits: jax.Array, k: int
) -> Tuple[jax.Array, jax.Array]:
    """Top-k alternatives under the UNtempered model distribution (what
    OpenAI's ``top_logprobs`` reports), with the same non-finite-row
    sanitization as :func:`sample_logits`. logits: [B, V] f32.

    Returns (token ids [B, k] int32, logprobs [B, k] f32, sorted desc).
    """
    finite = jnp.isfinite(logits)
    row_ok = jnp.any(finite, axis=-1, keepdims=True)
    logits = jnp.where(finite, logits, -jnp.inf)
    logits = jnp.where(row_ok, logits, 0.0)
    lps = jax.nn.log_softmax(logits, axis=-1)
    top_lps, top_ids = jax.lax.top_k(lps, k)
    return top_ids.astype(jnp.int32), top_lps
