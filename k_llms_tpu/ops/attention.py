"""Flash attention for TPU (Pallas) with an XLA reference path.

The prefill hot loop is a classic flash-attention pattern: tile Q and K/V into
VMEM blocks, keep running max/sum/accumulator scratch across the K grid axis
(TPU grids execute sequentially, so scratch persists), and never materialize
the [Sq, Sk] score matrix in HBM. GQA is handled by mapping each query head's
K/V BlockSpec onto its shared kv head — no head replication in memory.

`attention_xla` is the always-available reference implementation (also the
numerical oracle in tests, where the kernel runs in interpret mode on CPU).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)


def attention_xla(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    key_mask: Optional[jax.Array] = None,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Reference attention. q: [B, QH, Sq, D]; k/v: [B, KVH, Sk, D];
    key_mask: [B, Sk] booleans. Returns [B, QH, Sq, D] (f32)."""
    B, QH, Sq, D = q.shape
    KVH = k.shape[1]
    G = QH // KVH
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)

    qg = q.reshape(B, KVH, G, Sq, D)
    scores = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    Sk = k.shape[2]
    if causal:
        cmask = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        scores = jnp.where(cmask[None, None, None], scores, NEG_INF)
    if key_mask is not None:
        scores = jnp.where(key_mask[:, None, None, None, :].astype(bool), scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgqk,bhkd->bhgqd", weights, v.astype(jnp.float32))
    return out.reshape(B, QH, Sq, D)


def _flash_kernel(
    keylen_ref,  # [B, 1] int32 in SMEM: valid (prefix) key count per batch row
    window_ref,  # [1, 1] int32 in SMEM: sliding window (2^30 = no window)
    qoff_ref,  # [1, 1] int32 in SMEM: absolute position of query row 0
    q_ref,  # [1, 1, block_q, D]
    k_ref,  # [1, 1, block_k, D]
    v_ref,  # [1, 1, block_k, D]
    o_ref,  # [1, 1, block_q, D]
    acc_ref,  # VMEM scratch [block_q, D] f32
    m_ref,  # VMEM scratch [block_q, 1] f32 running max
    l_ref,  # VMEM scratch [block_q, 1] f32 running sum
    *,
    sm_scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    softcap: Optional[float],
):
    bi = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # q_offset shifts queries to ABSOLUTE positions (continuation prefill:
    # query row 0 sits at position prefix_len over a key space that starts at
    # the sequence's position 0). Zero for ordinary same-origin prefill.
    q_start = qi * block_q + qoff_ref[0, 0]
    k_start = ki * block_k

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * sm_scale  # [block_q, block_k]
        if softcap is not None:  # Gemma-2 attention softcap
            s = softcap * jnp.tanh(s / softcap)

        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        valid = cols < keylen_ref[bi, 0]
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        if causal:
            valid = jnp.logical_and(valid, cols <= rows)
        # Sliding window (dynamic so alternating-layer configs can scan one
        # kernel): query at row sees keys in (row - W, row].
        valid = jnp.logical_and(valid, cols > rows - window_ref[0, 0])
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Renormalize the old accumulator, fold in the new block.
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [block_q, block_k]
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p,
            v_ref[0, 0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    if causal:
        # Skip K blocks entirely above the causal diagonal (q_start already
        # carries the traced absolute offset, so this stays exact under it).
        pl.when(k_start <= q_start + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ki == pl.num_programs(3) - 1)
    def _finalize():
        l = l_ref[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out = acc_ref[:] / safe_l
        # A row with NO valid key anywhere (m never left the floor — e.g. a
        # padded query whose window misses the valid key range entirely)
        # accumulated exp(0)=1 garbage; emit zeros for it instead.
        out = jnp.where(m_ref[:] == NEG_INF, 0.0, out)
        o_ref[0, 0] = out.astype(o_ref.dtype)


def _decode_prefix_kernel(
    keylen_ref,  # [R, 1] int32 in SMEM: valid prefix length per request
    q_ref,  # [1, KVH, QR, D] — all of one request's query rows, per kv head
    k_ref,  # [1, block_k, KVH, D]
    v_ref,  # [1, block_k, KVH, D]
    o_ref,  # [1, KVH, QR, D] f32 (normalized within the prefix phase)
    m_o_ref,  # [1, KVH, QR] f32 running max (for the caller's logsumexp merge)
    l_o_ref,  # [1, KVH, QR] f32 softmax denominator at m
    acc_ref,  # VMEM scratch [KVH, QR, D] f32
    m_ref,  # VMEM scratch [KVH, QR] f32
    l_ref,  # VMEM scratch [KVH, QR] f32
    *,
    sm_scale: float,
    block_k: int,
    kv_heads: int,
):
    # Grid (R, key blocks): every block takes FULL (KVH, D) trailing axes, so
    # TPU tiling constraints are met for any head count / head dim, each KV
    # block streams from HBM exactly once, and the kv-head loop unrolls inside
    # the kernel over VMEM-resident data.
    r = pl.program_id(0)
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    QR = q_ref.shape[2]
    cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (QR, block_k), 1)
    valid = cols < keylen_ref[r, 0]

    for h in range(kv_heads):  # static unroll
        q = q_ref[0, h].astype(jnp.float32)  # [QR, D]
        k = k_ref[0, :, h, :].astype(jnp.float32)  # [block_k, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = jnp.where(valid, s * sm_scale, NEG_INF)  # [QR, block_k]

        m_prev = m_ref[h][:, None]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[h] = l_ref[h] * alpha[:, 0] + jnp.sum(p, axis=1)
        acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
            p,
            v_ref[0, :, h, :].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[h] = m_new[:, 0]

    @pl.when(ki == pl.num_programs(1) - 1)
    def _finalize():
        l = l_ref[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = acc_ref[:] / safe_l[:, :, None]
        m_o_ref[0] = m_ref[:]
        l_o_ref[0] = l_ref[:]


def decode_prefix_attention(
    q: jax.Array,
    prefix_k: jax.Array,
    prefix_v: jax.Array,
    prompt_lens: jax.Array,
    *,
    sm_scale: Optional[float] = None,
    block_k: int = 128,
    interpret: bool = False,
):
    """Decode-step attention over the SHARED-PREFIX KV, as a Pallas kernel.

    The decode hot loop splits attention into (a) the prompt prefix — hundreds
    of keys, stored once per request and shared by all its samples — and (b)
    the per-row generated tail (tens of keys). This kernel handles phase (a),
    where the HBM traffic is: the grid walks (request, kv head, key block) so
    each prefix block is streamed from HBM ONCE per (request, head) and hit by
    the request's whole [n_per*G, D] query tile on the MXU — versus one read
    per batch row in a naive layout. Phase (b) plus an exact logsumexp merge
    stay in XLA (`models/llama.py::_block`).

    q: [B, QH, D] (rows request-major, B % R == 0); prefix_k/v:
    [R, P, KVH, D]; prompt_lens: [R] valid key counts. Returns
    (out [B, QH, D] f32 — normalized within the prefix phase, m [B, QH],
    l [B, QH]) for the caller's merge.
    """
    B, QH, D = q.shape
    R, P, KVH, _ = prefix_k.shape
    G = QH // KVH
    n_per = B // R
    QR = n_per * G
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
    block_k = min(block_k, P)

    # Request-major query tile per kv head: [R, KVH, n_per*G, D]. Row (r, h,
    # i*G + g) is batch row r*n_per + i, query head h*G + g.
    q4 = q.reshape(R, n_per, KVH, G, D).transpose(0, 2, 1, 3, 4).reshape(R, KVH, QR, D)

    grid = (R, pl.cdiv(P, block_k))
    kernel = functools.partial(
        _decode_prefix_kernel, sm_scale=scale, block_k=block_k, kv_heads=KVH
    )

    out, m, l = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((R, KVH, QR, D), jnp.float32),
            jax.ShapeDtypeStruct((R, KVH, QR), jnp.float32),
            jax.ShapeDtypeStruct((R, KVH, QR), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((R, 1), lambda r, ki: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, KVH, QR, D), lambda r, ki: (r, 0, 0, 0)),
            pl.BlockSpec((1, block_k, KVH, D), lambda r, ki: (r, ki, 0, 0)),
            pl.BlockSpec((1, block_k, KVH, D), lambda r, ki: (r, ki, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, KVH, QR, D), lambda r, ki: (r, 0, 0, 0)),
            pl.BlockSpec((1, KVH, QR), lambda r, ki: (r, 0, 0)),
            pl.BlockSpec((1, KVH, QR), lambda r, ki: (r, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((KVH, QR, D), jnp.float32),
            pltpu.VMEM((KVH, QR), jnp.float32),
            pltpu.VMEM((KVH, QR), jnp.float32),
        ],
        interpret=interpret,
    )(prompt_lens.astype(jnp.int32).reshape(R, 1), q4, prefix_k, prefix_v)

    def back(x):  # [R, KVH, QR, ...] -> [B, QH, ...]
        tail = x.shape[3:]
        x = x.reshape(R, KVH, n_per, G, *tail).swapaxes(1, 2)
        return x.reshape(B, QH, *tail)

    return back(out), back(m), back(l)


NO_WINDOW = 1 << 30


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    key_lengths: Optional[jax.Array] = None,
    sm_scale: Optional[float] = None,
    softcap: Optional[float] = None,
    window=None,
    q_offset=None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Pallas flash attention. q: [B, QH, Sq, D]; k/v: [B, KVH, Sk, D];
    key_lengths: [B] int32 — keys at positions >= length are masked (the
    padding pattern our engine produces; a prefix length rides SMEM where an
    arbitrary mask array would break TPU tiling). ``softcap`` applies Gemma-2's
    cap*tanh(s/cap) to the scaled scores. ``window`` limits each query to the
    last W keys — a static int or a TRACED scalar, so alternating-window
    configs (Gemma-2) can select W per scanned layer without recompiling.
    ``q_offset`` (static int or traced scalar) is the absolute position of
    query row 0 — the continuation-prefill mode, where a suffix of queries
    attends a key space rooted at position 0; causality and windows are
    evaluated at row + q_offset. Returns [B, QH, Sq, D].

    Sq/Sk pad to block multiples internally; GQA maps query head h onto kv head
    h // (QH // KVH) via the BlockSpec index maps.
    """
    B, QH, Sq, D = q.shape
    KVH, Sk = k.shape[1], k.shape[2]
    G = QH // KVH
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)

    block_q = max(8, min(block_q, Sq))
    block_k = max(8, min(block_k, Sk))
    Sq_pad = pl.cdiv(Sq, block_q) * block_q
    Sk_pad = pl.cdiv(Sk, block_k) * block_k

    if key_lengths is None:
        key_lengths = jnp.full((B,), Sk, jnp.int32)
    key_lengths = key_lengths.astype(jnp.int32).reshape(B, 1)
    if window is None:
        window = NO_WINDOW
    window_arr = jnp.asarray(window, jnp.int32).reshape(1, 1)
    qoff_arr = jnp.asarray(0 if q_offset is None else q_offset, jnp.int32).reshape(1, 1)
    if Sk_pad != Sk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, Sk_pad - Sk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, Sk_pad - Sk), (0, 0)))
    if Sq_pad != Sq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, Sq_pad - Sq), (0, 0)))

    grid = (B, QH, Sq_pad // block_q, Sk_pad // block_k)

    kernel = functools.partial(
        _flash_kernel,
        sm_scale=scale,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        softcap=softcap,
    )

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, QH, Sq_pad, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((B, 1), lambda b, h, qi, ki: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda b, h, qi, ki: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda b, h, qi, ki: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, qi, ki: (b, h // G, ki, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, qi, ki: (b, h // G, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(key_lengths, window_arr, qoff_arr, q, k, v)

    return out[:, :, :Sq, :]


def gather_kv_pages(
    pool_k: jax.Array,
    pool_v: jax.Array,
    slot_idx: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Block-table gather: materialize logical KV rows from a flat page pool.

    pool_k/pool_v: one layer's pool, ``[total_pages * page_size, KVH, D]``;
    slot_idx: int32 flat slot indices of any shape (typically ``[B, S]`` —
    each row's block table expanded to per-position slots). Returns
    ``(k, v)`` shaped ``slot_idx.shape + (KVH, D)``.

    Out-of-table positions point into the trash page (page 0) by convention;
    their values are arbitrary-but-finite and every consumer masks their
    scores to ``NEG_INF`` before the softmax max, so they contribute an exact
    0.0 to the output — which is what keeps the paged attention path
    byte-identical to the dense one.
    """
    return jnp.take(pool_k, slot_idx, axis=0), jnp.take(pool_v, slot_idx, axis=0)
