"""Device-side ops: sampling, attention kernels, ring attention."""

from .sampling import sample_logits

__all__ = ["sample_logits"]
