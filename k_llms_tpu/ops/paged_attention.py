"""Fused paged-decode attention: block-table gather inside the QK^T.V loop.

The paged KV pool (engine/paging.py) stores every row's keys and values as
pool pages addressed through per-row block tables. Before this op existed the
layer scan materialized the gathered K/V (`take_along_axis` twice per layer)
and then ran dense attention over the copy — the gather bandwidth alone is
2 * bytes(KV) per decode step per layer at 8B widths. The Pallas kernel here
does what vLLM's PagedAttention does on GPU: the grid walks (row, page) and
each page block's HBM read is indexed *through the block table* by the
BlockSpec index_map, so the gather IS the attention's K/V load — no
materialized copy, one online-softmax pass, and the current step's fresh
column (not yet scattered into the pool) folded in at finalize.

Two implementations, one contract:

- ``paged_decode_attention_pallas``: the fused kernel. Uses scalar prefetch
  (page tables + per-row lengths/phase) to drive the data BlockSpecs. TPU
  only in production; ``interpret=True`` exists for the differential tests.
- ``paged_decode_attention_xla``: jittable pure-XLA reference with identical
  semantics — and byte-identical to the dense `_block` decode math (same op
  order, same masks), which is what the serving path runs everywhere Pallas
  is unavailable (tier-1 CI is `JAX_PLATFORMS=cpu`; interpret mode is never
  used for serving).

Selection is ``resolve_paged_attention_impl`` (backed by
``BackendConfig.paged_attention_impl``): "xla" | "pallas" | "auto", with an
automatic COUNTED fallback (``kernel.paged_attn_fallback.<reason>``, where
the suffix names what blocked the kernel: failpoint / softcap /
sliding_window / platform) when "pallas" is requested but can't run; "auto"
choosing XLA off-TPU is the documented CPU posture, not a fallback, so it is
not counted. The ``ops.paged_attn`` failpoint forces the fallback branch for
drills.

Masking contract (shared with `gather_kv_pages`): out-of-table positions
point into the trash page; their values are arbitrary-but-finite and every
consumer forces their scores to ``NEG_INF`` before the softmax max, so they
contribute an exact 0.0 — the invariant behind paged == dense bit-equality.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..reliability import failpoints as _failpoints
from ..utils.observability import KERNEL_EVENTS
from .attention import NEG_INF, decode_prefix_attention, gather_kv_pages

#: Values accepted by ``BackendConfig.paged_attention_impl`` /
#: ``LocalEngine(paged_attention_impl=...)``. "pallas_interpret" is a
#: tests-only extra understood by ``paged_verify_step`` — never returned by
#: :func:`resolve_paged_attention_impl`, never run in the serving path.
PAGED_ATTENTION_IMPLS = ("auto", "pallas", "xla")


def resolve_paged_attention_impl(requested: str, *, config=None) -> str:
    """Pick the paged-attention implementation for the current process.

    requested: "auto" | "pallas" | "xla"; config: optional ModelConfig — a
    model using attention softcap or sliding windows is outside the kernel's
    support and resolves to "xla". Resolution is host-side and happens once
    per loop/launch build, not per step. An explicit "pallas" request that
    cannot be honored records ``kernel.paged_attn_fallback.<reason>``, where
    the reason distinguishes config-driven fallbacks (``softcap``,
    ``sliding_window`` — the model is outside the kernel's support) from
    environment-driven ones (``platform`` — no TPU) and drills
    (``failpoint``); "auto" picking XLA off-TPU is the expected CPU posture
    and is NOT counted. The ``ops.paged_attn`` failpoint (action
    ``fallback``) forces the counted fallback for observability drills.
    """
    if requested not in PAGED_ATTENTION_IMPLS:
        raise ValueError(
            f"paged_attention_impl must be one of {PAGED_ATTENTION_IMPLS}, "
            f"got {requested!r}"
        )
    spec = _failpoints.fire("ops.paged_attn")
    if spec is not None and spec.action == "fallback":
        KERNEL_EVENTS.record("kernel.paged_attn_fallback.failpoint")
        return "xla"
    if requested == "xla":
        return "xla"
    if config is not None and config.attn_softcap is not None:
        blocked: Optional[str] = "softcap"
    elif config is not None and config.sliding_window is not None:
        blocked = "sliding_window"
    else:
        blocked = None
    if jax.default_backend() == "tpu" and blocked is None:
        return "pallas"
    if requested == "pallas":
        KERNEL_EVENTS.record(f"kernel.paged_attn_fallback.{blocked or 'platform'}")
    return "xla"


def note_paged_attn_dispatch(impl: str, n: int = 1) -> None:
    """Count a paged-attention dispatch (one per decode launch / continuous
    paged step, host-side — never inside jit). Interpret-mode runs count as
    pallas: the kernel code path is what's being exercised."""
    if impl in ("pallas", "pallas_interpret"):
        KERNEL_EVENTS.record("kernel.paged_attn_pallas_dispatch", n)
    else:
        KERNEL_EVENTS.record("kernel.paged_attn_xla_dispatch", n)


# ---------------------------------------------------------------------------
# XLA reference (always available; the serving path off-TPU)
# ---------------------------------------------------------------------------


def paged_decode_attention_xla(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    prefix_idx: jax.Array,
    gen_idx: jax.Array,
    new_k: jax.Array,
    new_v: jax.Array,
    write_index: jax.Array,
    key_mask: jax.Array,
    prefix_mask: jax.Array,
    *,
    sm_scale: float,
    softcap: Optional[float] = None,
    prefix_lengths: Optional[jax.Array] = None,
    flash_prefix: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Reference paged decode attention, byte-identical to the dense path.

    q/new_k/new_v: this step's post-RoPE projections, ``[B, Sq, QH|KVH, D]``
    (``Sq == 1`` on the decode hot path); pool_k/pool_v: ONE layer's flat
    page pool ``[total_pages * page_size, KVH, D]``; prefix_idx
    ``[B|R, P]`` / gen_idx ``[B, G]``: flat pool slots per logical position
    (an ``[R, P]`` prefix is shared request-major, exactly like the dense
    shared-prefix cache); write_index ``[B]``: each row's write offset into
    its gen slots; key_mask ``[B, Sq, G]`` / prefix_mask ``[B, Sq, P]``:
    the same masks the dense `_block` receives.

    The op order — gather, per-row fresh-column insert, masked scores,
    concatenated softmax (or the flash-prefix logsumexp merge when
    ``flash_prefix``) — replicates `models/llama.py::_block`'s decode branch
    operation for operation, so outputs are bit-identical to dense attention
    on equal inputs. Returns attn ``[B, Sq, QH, D]`` f32.
    """
    from ..models.llama import (
        _gqa_scores,
        _gqa_scores_shared,
        _gqa_values,
        _gqa_values_shared,
        _merge_prefix_tail,
        _softcap,
    )

    pk, pv = gather_kv_pages(pool_k, pool_v, prefix_idx)  # [B|R, P, KVH, D]
    gk, gv = gather_kv_pages(pool_k, pool_v, gen_idx)  # [B, G, KVH, D]
    # The dense path's per-row cache write: the freshly computed column lands
    # at each row's own offset before attention reads it.
    row_update = jax.vmap(
        lambda c, kk, off: lax.dynamic_update_slice_in_dim(c, kk, off, axis=0)
    )
    gk = row_update(gk, new_k.astype(gk.dtype), write_index)
    gv = row_update(gv, new_v.astype(gv.dtype), write_index)

    if flash_prefix:
        out_p, m_p, l_p = decode_prefix_attention(
            q[:, 0],
            pk,
            pv,
            prefix_lengths,
            sm_scale=sm_scale,
            interpret=interpret,
        )
        return _merge_prefix_tail(
            q,
            gk,
            gv,
            key_mask,
            sm_scale,
            out_p[:, :, None],
            m_p[:, :, None],
            l_p[:, :, None],
        )

    scores = _gqa_scores(q, gk) * sm_scale  # [B, QH, Sq, G] f32
    if softcap is not None:
        scores = _softcap(scores, softcap)
    neg = jnp.finfo(jnp.float32).min
    scores = jnp.where(key_mask[:, None, :, :], scores, neg)
    p_scores = _gqa_scores_shared(q, pk) * sm_scale  # [B, QH, Sq, P]
    if softcap is not None:
        p_scores = _softcap(p_scores, softcap)
    p_scores = jnp.where(prefix_mask[:, None, :, :], p_scores, neg)
    all_scores = jnp.concatenate([p_scores, scores], axis=-1)
    weights = jax.nn.softmax(all_scores, axis=-1)
    P = pk.shape[1]
    return _gqa_values_shared(weights[..., :P], pv) + _gqa_values(
        weights[..., P:], gv
    )


# ---------------------------------------------------------------------------
# Page-table derivation (shared by the Pallas caller)
# ---------------------------------------------------------------------------


def paged_attention_page_tables(
    prefix_idx: jax.Array, gen_idx: jax.Array, page_size: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Derive per-row PAGE tables from flat-SLOT index maps.

    The engine's index maps carry one flat slot per logical position
    (position p -> page * page_size + offset). The kernel wants the page
    granularity back: ``prefix_pages [B|R, ceil(P/ps)]``, ``gen_pages
    [B, ceil(G/ps) + 1]`` and ``gen_phase [B]`` — the in-page offset of gen
    position 0 (``plen % ps`` for the continuous layout where generated
    tokens continue the prompt's last partial page; 0 for the coalesced
    fresh-page layout). The +1 gen page absorbs the phase shift's worst
    case. Pages for fully-masked table regions are whatever slot the map
    pointed at (typically trash) — the kernel's validity predicate masks
    every position they cover, so their contents are don't-care.

    Traceable (pure jnp); layer-invariant, so callers hoist it outside the
    layer scan.
    """
    ps = page_size
    prefix_pages = prefix_idx[..., ::ps] // ps  # [B|R, ceil(P/ps)]
    G = gen_idx.shape[-1]
    NG = -(-G // ps) + 1
    phase = gen_idx[:, :1] % ps  # [B, 1]
    starts = jnp.arange(NG, dtype=jnp.int32)[None, :] * ps - phase  # [B, NG]
    src = jnp.clip(starts, 0, G - 1)
    gen_pages = jnp.take_along_axis(gen_idx, src, axis=1) // ps  # [B, NG]
    return (
        prefix_pages.astype(jnp.int32),
        gen_pages.astype(jnp.int32),
        phase[:, 0].astype(jnp.int32),
    )


# ---------------------------------------------------------------------------
# Fused Pallas kernel
# ---------------------------------------------------------------------------


def _paged_decode_kernel(
    # scalar prefetch (SMEM) -------------------------------------------------
    tables_ref,  # [B, NP + NG] int32: pool page per (row, page block)
    plen_ref,  # [B] int32: valid prefix length per row
    glen_ref,  # [B] int32: generated count per row (current token excluded)
    phase_ref,  # [B] int32: in-page offset of gen position 0
    # data -------------------------------------------------------------------
    q_ref,  # [1, KVH, G, D] — one row's queries, grouped per kv head
    k_ref,  # [1, page_size, KVH, D] — pool page tables_ref[b, j]
    v_ref,  # [1, page_size, KVH, D]
    nk_ref,  # [1, KVH, D] — this step's fresh key column (not yet in pool)
    nv_ref,  # [1, KVH, D]
    o_ref,  # [1, KVH, G, D] f32
    # VMEM scratch -----------------------------------------------------------
    acc_ref,  # [KVH, G, D] f32
    m_ref,  # [KVH, G] f32 running max
    l_ref,  # [KVH, G] f32 running denominator
    *,
    sm_scale: float,
    page_size: int,
    num_prefix_pages: int,
    kv_heads: int,
):
    # Grid (row, page block): pages run prefix-first then gen; TPU grids
    # execute sequentially so the online-softmax scratch persists across the
    # page axis. The block-table indirection already happened in the
    # BlockSpec index_map — by the time this body runs, k_ref/v_ref ARE the
    # right page.
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    Gq = q_ref.shape[2]
    offs = lax.broadcasted_iota(jnp.int32, (Gq, page_size), 1)
    is_prefix = j < num_prefix_pages
    # Logical position of each in-page slot: prefix pages count from 0;
    # gen pages are phase-shifted (gen position g lives at in-page offset
    # (phase + g) % ps of gen page (phase + g) // ps).
    pos = jnp.where(
        is_prefix,
        j * page_size + offs,
        (j - num_prefix_pages) * page_size + offs - phase_ref[b],
    )
    limit = jnp.where(is_prefix, plen_ref[b], glen_ref[b])
    # TRASH_PAGE safety: any slot outside [0, limit) — padding, the phase
    # shift's dead lead-in, trash-retargeted table tails — scores NEG_INF
    # and contributes an exact 0.
    valid = (pos >= 0) & (pos < limit)

    for h in range(kv_heads):  # static unroll
        q = q_ref[0, h].astype(jnp.float32)  # [Gq, D]
        k = k_ref[0, :, h, :].astype(jnp.float32)  # [page_size, D]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = jnp.where(valid, s * sm_scale, NEG_INF)  # [Gq, page_size]

        m_prev = m_ref[h][:, None]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[h] = l_ref[h] * alpha[:, 0] + jnp.sum(p, axis=1)
        acc_ref[h] = acc_ref[h] * alpha + lax.dot_general(
            p,
            v_ref[0, :, h, :].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[h] = m_new[:, 0]

    @pl.when(j == pl.num_programs(1) - 1)
    def _finalize():
        # Fold in the CURRENT token's fresh K/V column — the caller hasn't
        # scattered it into the pool yet (the dense twin writes it into the
        # cache before attending; same visibility, no pool round-trip).
        for h in range(kv_heads):
            q = q_ref[0, h].astype(jnp.float32)  # [Gq, D]
            nk = nk_ref[0, h].astype(jnp.float32)  # [D]
            s = jnp.sum(q * nk[None, :], axis=1, keepdims=True) * sm_scale
            m_prev = m_ref[h][:, None]
            m_new = jnp.maximum(m_prev, s)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)  # [Gq, 1]
            l = l_ref[h] * alpha[:, 0] + p[:, 0]
            acc = acc_ref[h] * alpha + p * nv_ref[0, h].astype(jnp.float32)[None, :]
            safe_l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, h] = acc / safe_l[:, None]


def paged_decode_attention_pallas(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    prefix_pages: jax.Array,
    gen_pages: jax.Array,
    gen_phase: jax.Array,
    new_k: jax.Array,
    new_v: jax.Array,
    prompt_lens: jax.Array,
    gen_lens: jax.Array,
    *,
    page_size: int,
    sm_scale: float,
    interpret: bool = False,
) -> jax.Array:
    """Fused paged decode attention (``Sq == 1``).

    q: [B, QH, D]; pool_k/pool_v: one layer's flat pool
    [total_pages * page_size, KVH, D]; prefix_pages [B|R, NP] / gen_pages
    [B, NG] / gen_phase [B]: from :func:`paged_attention_page_tables`;
    new_k/new_v [B, KVH, D]: this step's fresh column; prompt_lens /
    gen_lens [B]: per-row valid counts. Returns [B, QH, D] f32 — the same
    normalized output the XLA reference produces (up to online-softmax
    float ordering; token-exact under greedy, pinned by the differential
    tests).
    """
    B, QH, D = q.shape
    KVH = pool_k.shape[1]
    G = QH // KVH
    ps = page_size
    npages = pool_k.shape[0] // ps
    if prefix_pages.shape[0] != B:  # [R, NP] shared prefix -> per-row table
        prefix_pages = jnp.repeat(
            prefix_pages, B // prefix_pages.shape[0], axis=0,
            total_repeat_length=B,
        )
    NP = prefix_pages.shape[1]
    NG = gen_pages.shape[1]
    tables = jnp.concatenate([prefix_pages, gen_pages], axis=1).astype(jnp.int32)

    q4 = q.reshape(B, KVH, G, D)  # query head h*G+g shares kv head h
    pk4 = pool_k.reshape(npages, ps, KVH, D)
    pv4 = pool_v.reshape(npages, ps, KVH, D)

    kernel = functools.partial(
        _paged_decode_kernel,
        sm_scale=sm_scale,
        page_size=ps,
        num_prefix_pages=NP,
        kv_heads=KVH,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, NP + NG),
        in_specs=[
            pl.BlockSpec((1, KVH, G, D), lambda b, j, *_: (b, 0, 0, 0)),
            pl.BlockSpec(
                (1, ps, KVH, D), lambda b, j, tables, *_: (tables[b, j], 0, 0, 0)
            ),
            pl.BlockSpec(
                (1, ps, KVH, D), lambda b, j, tables, *_: (tables[b, j], 0, 0, 0)
            ),
            pl.BlockSpec((1, KVH, D), lambda b, j, *_: (b, 0, 0)),
            pl.BlockSpec((1, KVH, D), lambda b, j, *_: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, KVH, G, D), lambda b, j, *_: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((KVH, G, D), jnp.float32),
            pltpu.VMEM((KVH, G), jnp.float32),
            pltpu.VMEM((KVH, G), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH, G, D), jnp.float32),
        interpret=interpret,
    )(
        tables,
        prompt_lens.astype(jnp.int32),
        gen_lens.astype(jnp.int32),
        gen_phase.astype(jnp.int32),
        q4,
        pk4,
        pv4,
        new_k,
        new_v,
    )
    return out.reshape(B, QH, D)
