"""w4a16 matmul: int4 weight-only quantization with a Pallas TPU kernel.

Autoregressive decode streams every weight byte from HBM each step, so the
decode ceiling is HBM bandwidth (the reference has no model layer at all — its
engine is the OpenAI HTTP API; this optimizes the local TPU engine's hot loop).
int8 already halves bf16 traffic; int4 halves the FOOTPRINT again. XLA cannot
fuse nibble unpacking into a dot (the unpacked bf16 operand materializes in
HBM, measured ~5x SLOWER than int8), so the unpack must happen in VMEM: this
kernel DMAs the packed [K/2, N] int8 payload block-by-block, sign-extends both
nibbles on the VPU, and feeds the MXU — HBM only ever sees 4-bit weights.

Measured role on v5e (llama-3-8b, n=32 decode): the int8 path already runs at
~75% of peak HBM bandwidth (13.7 ms/step), while the nibble unpack is
VPU-throughput-bound (~1-2 elements/lane/cycle over every weight), so w4a16
decodes ~25% SLOWER (17.4 ms/step) despite streaming half the bytes; the
`pltpu.bitcast`-to-int4 unpack and an XLA `s4` dot were both measured slower
still. int4 is therefore the CAPACITY config — 8B weights in ~5.0 GB instead
of ~8.6 GB (room for larger KV caches, longer contexts, or 13B-class models
on one 16 GB chip) — and int8 is the latency config.

Storage format (see :func:`pack_int4`): weights are grouped along the
contraction axis (GROUP=128 rows per group, one f32 scale per (group, out)
column — group-wise symmetric quantization, the AWQ/llama.cpp-Q4 layout). A
group's rows 0..63 live in the LOW nibbles and rows 64..127 in the HIGH
nibbles of the same packed byte rows, so the kernel unpack is a sublane
concatenate instead of an interleave (TPU-tiling friendly).

The int4 values are clipped to [-7, 7] (symmetric, no -8) and the scale is
applied AFTER the group dot in f32 — the MXU sees exact small integers in
bf16, so no precision is lost to the weight cast.
"""

from __future__ import annotations

import functools
import inspect
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # 0.4.x: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

# 0.4.x names it TPUCompilerParams; same kwargs for the fields we use.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

GROUP = 128  # contraction rows per quantization group (one scale each)
_HALF = GROUP // 2


@jax.tree_util.register_pytree_node_class
class Q4Tensor:
    """Packed int4 weight: ``q`` int8 [..., K/2, N] (two nibbles per byte along
    the contraction axis), ``scale`` f32 [..., K/GROUP, N].

    ``part``/``mesh`` are STATIC pytree metadata (not serialized — the engine
    re-marks after checkpoint load) describing how the weight is sharded under
    tensor parallelism: ``part="col"`` = output columns over the model axis
    (Megatron column-parallel), ``part="row"`` = contraction rows over the
    model axis (row-parallel; the sharded matmul psums). None = unsharded —
    ``qdot`` then runs the plain single-shard kernel.
    """

    def __init__(self, q, scale, part: Optional[str] = None, mesh=None):
        self.q = q
        self.scale = scale
        self.part = part
        self.mesh = mesh

    def tree_flatten(self):
        return (self.q, self.scale), (self.part, self.mesh)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, part=aux[0], mesh=aux[1])

    def __repr__(self):
        return f"Q4Tensor(q={self.q!r}, scale={self.scale!r}, part={self.part!r})"

    @property
    def k_dim(self) -> int:
        return self.q.shape[-2] * 2

    @property
    def shape(self):
        return self.q.shape[:-2] + (self.k_dim, self.q.shape[-1])

    @property
    def dtype(self):
        return self.q.dtype


def supports_int4(k: int) -> bool:
    """The kernel needs whole groups and at least one 256-row K block."""
    return k % 256 == 0


def pack_int4(w: jax.Array) -> Q4Tensor:
    """Group-wise symmetric int4 quantization of ``w`` [..., K, N].

    Per group of GROUP contraction rows: scale = amax/7, values round-clipped
    to [-7, 7]. Rows [0, 64) of each group pack into low nibbles, rows
    [64, 128) into high nibbles of the same byte rows.
    """
    *lead, K, N = w.shape
    if K % GROUP != 0:
        raise ValueError(f"contraction dim {K} not a multiple of group {GROUP}")
    g = w.astype(jnp.float32).reshape(*lead, K // GROUP, GROUP, N)
    amax = jnp.max(jnp.abs(g), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax / 7.0, 1.0)
    q = jnp.clip(jnp.round(g / scale), -7, 7).astype(jnp.int8)
    lo = q[..., :_HALF, :]
    hi = q[..., _HALF:, :]
    packed = (lo & 0xF) | (hi << 4)
    packed = packed.reshape(*lead, K // 2, N)
    return Q4Tensor(q=packed, scale=scale[..., 0, :].reshape(*lead, K // GROUP, N))


def unpack_int4(w: Q4Tensor) -> jax.Array:
    """Dequantize to f32 [..., K, N] (reference/off-TPU path)."""
    *lead, Kh, N = w.q.shape
    p = w.q.astype(jnp.int32).reshape(*lead, Kh * 2 // GROUP, _HALF, N)
    lo = ((p & 0xF) ^ 8) - 8
    hi = p >> 4
    q = jnp.concatenate([lo, hi], axis=-2)  # [..., K/GROUP, GROUP, N]
    deq = q.astype(jnp.float32) * w.scale[..., None, :]
    return deq.reshape(*lead, Kh * 2, N)


def _w4_kernel(x_ref, qp_ref, sc_ref, o_ref, acc_ref, *, groups: int, out_dtype):
    """Grid (row blocks, N blocks, K blocks); K innermost so the accumulator
    scratch survives the K walk for each (row, N) tile."""
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    for g in range(groups):  # static unroll over groups in this K block
        p = qp_ref[g * _HALF : (g + 1) * _HALF, :].astype(jnp.int32)
        lo = ((p & 0xF) ^ 8) - 8
        hi = p >> 4  # arithmetic shift of the sign-extended byte
        w = jnp.concatenate([lo, hi], axis=0).astype(jnp.bfloat16)  # [GROUP, bn]
        xg = x_ref[:, g * GROUP : (g + 1) * GROUP]
        s = jax.lax.dot_general(
            xg, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_ref[:] += s * sc_ref[g, :][None, :]

    @pl.when(kb == pl.num_programs(2) - 1)
    def _emit():
        o_ref[:] = acc_ref[:].astype(out_dtype)


# Kernel grid blocking choices (largest-first; _pick takes the first that
# divides). int4_mesh_compatible derives its slow-shard advisory from these,
# so changing them here keeps the two in sync.
KERNEL_K_BLOCKS = (1024, 512, 256)
KERNEL_N_BLOCKS = (512, 256, 128)


def _pick(total: int, choices) -> int:
    for c in choices:
        if total % c == 0:
            return c
    return 0


def w4_matmul(
    x: jax.Array,
    w: Q4Tensor,
    *,
    block_rows: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """``x @ dequant(w)`` with 4-bit HBM traffic. x: [rows, K] (bf16/f32);
    returns [rows, N] in x.dtype. Falls back to the XLA dequant path when the
    shape doesn't fit the kernel's blocking (tiny test models)."""
    rows, K = x.shape
    Kh, N = w.q.shape
    assert K == Kh * 2, (K, w.q.shape)

    block_k = _pick(K, KERNEL_K_BLOCKS)
    block_n = _pick(N, KERNEL_N_BLOCKS)
    if not block_k or not block_n:
        return (x.astype(jnp.float32) @ unpack_int4(w)).astype(x.dtype)

    # bf16 VMEM tiles are (16, 128): keep the row block a multiple of 16.
    rp = max(16, min(block_rows, ((rows + 15) // 16) * 16))
    rows_pad = pl.cdiv(rows, rp) * rp
    if rows_pad != rows:
        x = jnp.pad(x, ((0, rows_pad - rows), (0, 0)))

    grid = (rows_pad // rp, N // block_n, K // block_k)
    kernel = functools.partial(
        _w4_kernel, groups=block_k // GROUP, out_dtype=x.dtype
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows_pad, N), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rp, block_k), lambda rb, nb, kb: (rb, kb)),
            pl.BlockSpec((block_k // 2, block_n), lambda rb, nb, kb: (kb, nb)),
            pl.BlockSpec((block_k // GROUP, block_n), lambda rb, nb, kb: (kb, nb)),
        ],
        out_specs=pl.BlockSpec((rp, block_n), lambda rb, nb, kb: (rb, nb)),
        scratch_shapes=[pltpu.VMEM((rp, block_n), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x, w.q, w.scale)
    return out[:rows]


def w4_matmul_tp(x: jax.Array, w: Q4Tensor, *, interpret: bool = False) -> jax.Array:
    """``x @ dequant(w)`` with the kernel shard_mapped over the weight's
    tensor-parallel layout (``w.part``/``w.mesh`` — VERDICT r2 #7).

    - ``col``: output columns sharded over the model axis; each device runs
      the kernel on its [K, N/TP] shard, activations replicated over model.
    - ``row``: contraction rows sharded; activations arrive model-sharded on
      their last dim (the Megatron row-parallel input layout), each device
      contracts its K/TP rows and the partials psum over the model axis.
      Group alignment holds because K % (GROUP * TP) is enforced by
      ``int4_mesh_compatible`` — a quantization group never splits devices.
    Rows (the batch dim) stay sharded over the data axis throughout.
    """
    from ..parallel.mesh import DATA_AXIS, MODEL_AXIS

    mesh = w.mesh
    # Shard the batch rows over the data axis when they divide evenly (decode
    # batches, prefill sequences); odd row counts (the 1-row last-token logits
    # call) replicate over data instead.
    rows_axis = DATA_AXIS if x.shape[0] % mesh.shape[DATA_AXIS] == 0 else None
    if w.part == "col":
        in_specs = (
            P(rows_axis, None),
            P(None, MODEL_AXIS),
            P(None, MODEL_AXIS),
        )
        out_specs = P(rows_axis, MODEL_AXIS)

        def local(xs, q, s):
            return w4_matmul(xs, Q4Tensor(q=q, scale=s), interpret=interpret)

    elif w.part == "row":
        in_specs = (
            P(rows_axis, MODEL_AXIS),
            P(MODEL_AXIS, None),
            P(MODEL_AXIS, None),
        )
        out_specs = P(rows_axis, None)

        def local(xs, q, s):
            part = w4_matmul(xs, Q4Tensor(q=q, scale=s), interpret=interpret)
            return jax.lax.psum(part, MODEL_AXIS)

    else:  # pragma: no cover - callers gate on part
        raise ValueError(f"unknown partition kind {w.part!r}")

    # Disable the replication/varying-axes checker: pallas_call's out_shape
    # carries no varying-mesh-axes annotation, which it would otherwise
    # reject inside shard_map. The flag is check_vma on current jax and
    # check_rep on 0.4.x.
    check_kw = (
        {"check_vma": False}
        if "check_vma" in inspect.signature(_shard_map).parameters
        else {"check_rep": False}
    )
    return _shard_map(
        local, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **check_kw
    )(x, w.q, w.scale)
