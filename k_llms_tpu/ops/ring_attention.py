"""Ring attention: exact sequence-parallel attention over a mesh axis.

Long-context support the reference cannot have (its sequence length is the
provider's problem, SURVEY.md §5): shard the sequence across devices, keep Q
local, and rotate K/V chunks around the ring with ``ppermute`` while
accumulating flash-style online softmax state. Every chunk transfer overlaps a
compute step and rides ICI; memory per device is O(S/P), so context scales
linearly with the ring size.

Causality is handled with global positions: device d owns query positions
[d*S_local, (d+1)*S_local); at ring step i it holds the K/V chunk of device
(d - i) mod P.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

NEG_INF = float(jnp.finfo(jnp.float32).min)

# jax moved shard_map out of experimental (and introduced explicit
# varying-axis typing via lax.pvary) after 0.4.x; support both so the ring
# paths run on the 0.4-series CPU image as well as current TPU toolchains.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # 0.4.x: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

if hasattr(lax, "pvary"):
    _pvary = lax.pvary
else:  # 0.4.x infers replication instead of explicit varying-axis marks
    def _pvary(x, axes):
        return x


def _chunk_attention_update(q, k, v, q_pos, k_pos, causal, scale, acc, m, l):
    """One online-softmax accumulation step against a K/V chunk.

    q: [B, QH, Sq, D]; k/v: [B, KVH, Sk, D]; q_pos/k_pos: global positions.
    acc: [B, QH, Sq, D] f32; m/l: [B, QH, Sq, 1] f32.
    """
    B, QH, Sq, D = q.shape
    KVH = k.shape[1]
    G = QH // KVH

    qg = q.reshape(B, KVH, G, Sq, D)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k, preferred_element_type=jnp.float32)
    s = (s * scale).reshape(B, QH, Sq, -1)
    if causal:
        mask = k_pos[None, :] <= q_pos[:, None]  # [Sq, Sk]
        s = jnp.where(mask[None, None], s, NEG_INF)

    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pg = p.reshape(B, KVH, G, Sq, -1)
    delta = jnp.einsum("bhgqk,bhkd->bhgqd", pg, v.astype(jnp.float32)).reshape(
        B, QH, Sq, D
    )
    acc_new = acc * alpha + delta
    return acc_new, m_new, l_new


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Per-shard body (call inside shard_map). q: [B, QH, S_local, D];
    k/v: [B, KVH, S_local, D] — all sharded on the sequence axis."""
    B, QH, S_local, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
    p_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)

    q_pos = my_idx * S_local + jnp.arange(S_local)

    # pvary: the accumulators start identical on every device but become
    # device-varying inside the loop; shard_map's axis typing requires the
    # carry to be marked varying up front.
    acc0 = _pvary(jnp.zeros((B, QH, S_local, D), jnp.float32), (axis_name,))
    m0 = _pvary(jnp.full((B, QH, S_local, 1), NEG_INF, jnp.float32), (axis_name,))
    l0 = _pvary(jnp.zeros((B, QH, S_local, 1), jnp.float32), (axis_name,))

    perm = [(j, (j + 1) % p_size) for j in range(p_size)]

    def step(i, carry):
        acc, m, l, k_cur, v_cur = carry
        src = (my_idx - i) % p_size
        k_pos = src * S_local + jnp.arange(S_local)
        acc, m, l = _chunk_attention_update(
            q, k_cur, v_cur, q_pos, k_pos, causal, scale, acc, m, l
        )
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        return (acc, m, l, k_cur, v_cur)

    acc, m, l, _, _ = lax.fori_loop(0, p_size, step, (acc0, m0, l0, k, v))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return (acc / safe_l).astype(q.dtype)


def ring_decode_prefix(
    mesh: Mesh,
    q: jax.Array,
    prefix_k: jax.Array,
    prefix_v: jax.Array,
    prefix_len: jax.Array,
    *,
    seq_axis: str = "data",
    model_axis: str = "model",
    sm_scale: Optional[float] = None,
):
    """Decode-step attention over a SEQUENCE-SHARDED prefix: the ring decode
    half of O(S/P) long-context serving (the SP prefill already leaves its KV
    sharded over ``seq_axis``; this attends it in place instead of
    all-gathering a replicated copy).

    q: [B, QH, D] with B sharded over ``seq_axis`` (the decode batch layout)
    and QH over ``model_axis``; prefix_k/v: [1, S, KVH, D] with S over
    ``seq_axis`` and KVH over ``model_axis``; prefix_len: scalar valid key
    count. Queries stay put; K/V chunks rotate the ring (P-1 ppermute hops
    per decode step) with online-softmax accumulation. Returns
    (out [B, QH, D] f32 — normalized within the prefix phase, m [B, QH],
    l [B, QH]) — the same contract as ``decode_prefix_attention``, so the
    caller's exact logsumexp merge with the generated tail applies unchanged.
    """

    def local(q, pk, pv, plen):
        B_local, QH, D = q.shape
        S_local = pk.shape[1]
        KVH = pk.shape[2]
        G = QH // KVH
        scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
        p_size = lax.psum(1, seq_axis)
        my_idx = lax.axis_index(seq_axis)

        qg = q.astype(jnp.float32).reshape(B_local, KVH, G, D)
        # Accumulators become varying over every axis the inputs vary on
        # (sequence ring + model-sharded heads), so mark them up front.
        vary = tuple(a for a in (seq_axis, model_axis) if a in mesh.axis_names)
        acc0 = _pvary(jnp.zeros((B_local, QH, D), jnp.float32), vary)
        m0 = _pvary(jnp.full((B_local, QH), NEG_INF, jnp.float32), vary)
        l0 = _pvary(jnp.zeros((B_local, QH), jnp.float32), vary)

        perm = [(j, (j + 1) % p_size) for j in range(p_size)]

        def step(i, carry):
            acc, m, l, k_cur, v_cur = carry
            src = (my_idx - i) % p_size
            cols = src * S_local + jnp.arange(S_local)
            valid = cols < plen  # [S_local]
            # [B, KVH, G, D] x [S, KVH, D] -> [B, KVH, G, S]
            s = jnp.einsum(
                "bhgd,shd->bhgs", qg, k_cur[0].astype(jnp.float32),
                preferred_element_type=jnp.float32,
            ) * scale
            s = jnp.where(valid[None, None, None, :], s, NEG_INF)
            s = s.reshape(B_local, QH, S_local)

            m_cur = jnp.max(s, axis=-1)
            m_new = jnp.maximum(m, m_cur)
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[:, :, None])
            l_new = l * alpha + jnp.sum(p, axis=-1)
            delta = jnp.einsum(
                "bhgs,shd->bhgd",
                p.reshape(B_local, KVH, G, S_local),
                v_cur[0].astype(jnp.float32),
                preferred_element_type=jnp.float32,
            ).reshape(B_local, QH, D)
            acc_new = acc * alpha[:, :, None] + delta
            k_nxt = lax.ppermute(k_cur, seq_axis, perm)
            v_nxt = lax.ppermute(v_cur, seq_axis, perm)
            return (acc_new, m_new, l_new, k_nxt, v_nxt)

        acc, m, l, _, _ = lax.fori_loop(0, p_size, step, (acc0, m0, l0, pk, pv))
        safe_l = jnp.where(l == 0.0, 1.0, l)
        return acc / safe_l[:, :, None], m, l

    q_spec = P(seq_axis, model_axis, None)
    kv_spec = P(None, seq_axis, model_axis, None)
    out_spec = (q_spec, P(seq_axis, model_axis), P(seq_axis, model_axis))
    return _shard_map(
        local,
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, P()),
        out_specs=out_spec,
    )(q, prefix_k, prefix_v, prefix_len)


def ring_verify_prefix(
    mesh: Mesh,
    q: jax.Array,
    prefix_k: jax.Array,
    prefix_v: jax.Array,
    prefix_len: jax.Array,
    *,
    seq_axis: str = "data",
    model_axis: str = "model",
    sm_scale: Optional[float] = None,
):
    """Multi-query sibling of :func:`ring_decode_prefix` for speculative
    VERIFY steps: score a whole draft block (Sq = lookahead + 1 queries per
    row) against the sequence-sharded prefix in one ring pass, so spec decode
    composes with sp_decode instead of falling back to the normal loop.

    Every verify query sits past the prompt, so the prefix phase is
    NON-CAUSAL — all Sq queries see exactly the ``prefix_len`` valid keys,
    which is the same per-chunk valid-column mask the decode op uses; the ring
    structure is otherwise identical (K/V chunks rotate, queries stay put,
    online-softmax accumulation, still P-1 hops per verify rather than per
    token — the whole point of verifying blocks).

    q: [B, QH, Sq, D] with B sharded over ``seq_axis`` and QH over
    ``model_axis``; prefix_k/v: [1, S, KVH, D] with S over ``seq_axis``;
    prefix_len: scalar valid key count. Returns (out [B, QH, Sq, D] f32 —
    normalized within the prefix phase, m [B, QH, Sq], l [B, QH, Sq]) for the
    caller's exact logsumexp merge with the generated-KV tail.
    """

    def local(q, pk, pv, plen):
        B_local, QH, Sq, D = q.shape
        S_local = pk.shape[1]
        KVH = pk.shape[2]
        G = QH // KVH
        scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
        p_size = lax.psum(1, seq_axis)
        my_idx = lax.axis_index(seq_axis)

        qg = q.astype(jnp.float32).reshape(B_local, KVH, G, Sq, D)
        vary = tuple(a for a in (seq_axis, model_axis) if a in mesh.axis_names)
        acc0 = _pvary(jnp.zeros((B_local, QH, Sq, D), jnp.float32), vary)
        m0 = _pvary(jnp.full((B_local, QH, Sq), NEG_INF, jnp.float32), vary)
        l0 = _pvary(jnp.zeros((B_local, QH, Sq), jnp.float32), vary)

        perm = [(j, (j + 1) % p_size) for j in range(p_size)]

        def step(i, carry):
            acc, m, l, k_cur, v_cur = carry
            src = (my_idx - i) % p_size
            cols = src * S_local + jnp.arange(S_local)
            valid = cols < plen  # [S_local]
            # [B, KVH, G, Sq, D] x [S, KVH, D] -> [B, KVH, G, Sq, S]
            s = jnp.einsum(
                "bhgqd,shd->bhgqs", qg, k_cur[0].astype(jnp.float32),
                preferred_element_type=jnp.float32,
            ) * scale
            s = jnp.where(valid[None, None, None, None, :], s, NEG_INF)
            s = s.reshape(B_local, QH, Sq, S_local)

            m_cur = jnp.max(s, axis=-1)
            m_new = jnp.maximum(m, m_cur)
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[..., None])
            l_new = l * alpha + jnp.sum(p, axis=-1)
            delta = jnp.einsum(
                "bhgqs,shd->bhgqd",
                p.reshape(B_local, KVH, G, Sq, S_local),
                v_cur[0].astype(jnp.float32),
                preferred_element_type=jnp.float32,
            ).reshape(B_local, QH, Sq, D)
            acc_new = acc * alpha[..., None] + delta
            k_nxt = lax.ppermute(k_cur, seq_axis, perm)
            v_nxt = lax.ppermute(v_cur, seq_axis, perm)
            return (acc_new, m_new, l_new, k_nxt, v_nxt)

        acc, m, l, _, _ = lax.fori_loop(0, p_size, step, (acc0, m0, l0, pk, pv))
        safe_l = jnp.where(l == 0.0, 1.0, l)
        return acc / safe_l[..., None], m, l

    q_spec = P(seq_axis, model_axis, None, None)
    kv_spec = P(None, seq_axis, model_axis, None)
    out_spec = (q_spec, P(seq_axis, model_axis, None), P(seq_axis, model_axis, None))
    return _shard_map(
        local,
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, P()),
        out_specs=out_spec,
    )(q, prefix_k, prefix_v, prefix_len)


def ring_attention(
    mesh: Mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    seq_axis: str = "data",
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """shard_map wrapper: q [B, QH, S, D], k/v [B, KVH, S, D] with S sharded
    over ``seq_axis``. Exact (same result as full attention), memory O(S/P)."""
    spec = P(None, None, seq_axis, None)

    fn = functools.partial(
        ring_attention_local, axis_name=seq_axis, causal=causal, sm_scale=sm_scale
    )
    sharded = _shard_map(
        lambda q, k, v: fn(q, k, v),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return sharded(q, k, v)


def suffix_prefix_attention(
    mesh: Mesh,
    q: jax.Array,
    prefix_k: jax.Array,
    prefix_v: jax.Array,
    prefix_len: jax.Array,
    *,
    seq_axis: str = "data",
    model_axis: str = "model",
    sm_scale: Optional[float] = None,
):
    """Partial-softmax attention of REPLICATED suffix queries over a
    SEQUENCE-SHARDED prefix — the attention half of continuation prefill on an
    SP-resident cache entry (VERDICT r3 #6).

    q: [1, QH, Sq, D] replicated over ``seq_axis`` (QH over ``model_axis``);
    prefix_k/v: [1, S, KVH, D] with S over ``seq_axis``; prefix_len: scalar
    valid key count (the REUSED prefix length — may be shorter than the
    entry's stored length). Each device scores its local chunk and the
    partials merge with ONE pmax+psum logsumexp reduction (a one-shot
    continuation has no pipeline to overlap, so the ring rotation's P-1 hops
    buy nothing here). Returns (acc [1, QH, Sq, D] f32 — UNNORMALIZED,
    m [1, QH, Sq], l [1, QH, Sq]) for the caller's exact logsumexp merge with
    the suffix's causal self-attention. Never materializes more than O(S/P)
    prefix per device.
    """

    def local(q, pk, pv, plen):
        B, QH, Sq, D = q.shape
        S_loc, KVH = pk.shape[1], pk.shape[2]
        G = QH // KVH
        scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
        my_idx = lax.axis_index(seq_axis)
        cols = my_idx * S_loc + jnp.arange(S_loc)
        valid = cols < plen

        qg = q.astype(jnp.float32).reshape(B, KVH, G, Sq, D)
        s = jnp.einsum(
            "bhgqd,shd->bhgqs", qg, pk[0].astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ) * scale
        s = jnp.where(valid[None, None, None, None, :], s, NEG_INF)
        s = s.reshape(B, QH, Sq, S_loc)
        m_loc = jnp.max(s, axis=-1)
        p = jnp.exp(s - m_loc[..., None])
        # A device whose chunk has NO valid columns contributes l=0 (p rows
        # are exp(NEG_INF - NEG_INF) = 1 garbage otherwise).
        any_valid = jnp.any(valid)
        p = jnp.where(any_valid, p, 0.0)
        l_loc = jnp.sum(p, axis=-1)
        acc_loc = jnp.einsum(
            "bhgqs,shd->bhgqd",
            p.reshape(B, KVH, G, Sq, S_loc),
            pv[0].astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ).reshape(B, QH, Sq, D)

        m_g = lax.pmax(m_loc, seq_axis)
        w = jnp.exp(m_loc - m_g)
        l_g = lax.psum(l_loc * w, seq_axis)
        acc_g = lax.psum(acc_loc * w[..., None], seq_axis)
        return acc_g, m_g, l_g

    q_spec = P(None, model_axis, None, None)
    kv_spec = P(None, seq_axis, model_axis, None)
    return _shard_map(
        local,
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, P()),
        out_specs=(q_spec, P(None, model_axis, None), P(None, model_axis, None)),
    )(q, prefix_k, prefix_v, prefix_len)


def scatter_into_ring(
    mesh: Mesh,
    prefix: jax.Array,
    suffix: jax.Array,
    start: jax.Array,
    total_len: jax.Array,
    *,
    seq_axis: str = "data",
    model_axis: str = "model",
) -> jax.Array:
    """Write REPLICATED suffix rows into a SEQUENCE-SHARDED buffer in place:
    global row ``start + i`` takes ``suffix[:, i]`` for i < total_len - start;
    every other row keeps its value. prefix: [1, S, KVH, D] with S over
    ``seq_axis``; suffix: [1, Ssuf, KVH, D] replicated over ``seq_axis``.
    Each device updates only its own chunk — O(S/P), no gather."""

    def local(pk, sk, start, total):
        S_loc = pk.shape[1]
        my_idx = lax.axis_index(seq_axis)
        cols = my_idx * S_loc + jnp.arange(S_loc)
        idx = cols - start
        take = (idx >= 0) & (idx < sk.shape[1]) & (cols < total)
        vals = jnp.take(sk[0], jnp.clip(idx, 0, sk.shape[1] - 1), axis=0)
        return jnp.where(take[None, :, None, None], vals[None], pk)

    spec = P(None, seq_axis, model_axis, None)
    rep = P(None, None, model_axis, None)
    return _shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, rep, P(), P()),
        out_specs=spec,
    )(prefix, suffix, start, total_len)
