"""Prompt-lookup speculative decoding: draft proposal + acceptance (pure,
unit-testable device functions).

Extraction workloads — the framework's core use case — copy long spans of the
prompt into the output (field values, quoted names, numbers). Prompt-lookup
drafting (Saxena 2023-style; no draft model) exploits that: match the row's
trailing token bigram inside the prompt and propose the k tokens that followed
it there. Verification scores all k+1 positions in ONE forward
(`models/llama.py::verify_step`), so an accepted run of j drafts advances j+1
tokens for one weight-streaming pass — the decode loop is HBM-bound, so
acceptance translates ~directly into tokens/sec. A missed draft costs only the
few extra attention/logit positions (the weights stream once either way).

Acceptance is SAMPLE-AND-MATCH: position j's token is drawn from the model's
own conditional distribution p_j (fresh key per position); drafts only decide
how many of those draws were already conditioned on the right prefix and can
be emitted together. Every emitted token is therefore an exact sample of the
autoregressive chain at any temperature — no distribution drift, and greedy
decoding (temperature 0) reproduces normal decode output token-for-token.

Measured economics (llama-3-8b int8, n=32, v5e): a verify iteration costs the
decode step + ~1.6 ms per draft position (the lm_head projection over the
extra positions — weights stream once regardless), i.e. ~1.4x a plain step at
K=4. Break-even is ~0.5 accepted draft tokens per iteration; ~1.8 accepted
gives ~2x decode throughput. Prompt-copying extraction outputs on real
checkpoints typically accept 1.5-3 — hence opt-in
(`TpuBackend(speculative="prompt_lookup")`), and OFF for synthetic-weight
benchmarks where acceptance is ~0.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def propose_prompt_lookup(
    prompt: jax.Array,
    prompt_len: jax.Array,
    prev: jax.Array,
    cur: jax.Array,
    k: int,
    gen: "jax.Array | None" = None,
    gen_len: "jax.Array | None" = None,
) -> jax.Array:
    """Per-row drafts from the prompt and (optionally) the row's own generated
    text. prompt: [S] token buffer shared by all rows, or [B, S] PER-ROW
    buffers (coalesced batches: each request's rows search their own prompt);
    prompt_len: scalar valid length, or [B] per-row lengths with a 2D prompt;
    prev/cur: [B] the row's trailing bigram; gen: [B, T] generated-token
    buffers with valid lengths gen_len [B].

    Returns drafts [B, k] — the k tokens following the LAST occurrence of
    (prev, cur), preferring a match in the row's generated text (the more
    recent context; models repeating their own phrasing) over one in the
    prompt. Rows without a match, or draft positions past the source's end,
    fall back to repeating ``cur`` (harmless: the verify sampler just won't
    match them).
    """
    S = prompt.shape[-1]
    pos = jnp.arange(1, S)

    def from_prompt(p, plen, a, b):
        hit = (p[:-1] == a) & (p[1:] == b) & (pos < plen)
        last = jnp.max(jnp.where(hit, pos, -1))  # index of the bigram's 2nd token
        idx = last + 1 + jnp.arange(k)
        ok = (last >= 0) & (idx < plen)
        return jnp.where(ok, p[jnp.clip(idx, 0, S - 1)], b).astype(jnp.int32)

    if prompt.ndim == 2:
        drafts = jax.vmap(from_prompt)(
            prompt, jnp.broadcast_to(prompt_len, prev.shape), prev, cur
        )
    else:
        drafts = jax.vmap(lambda a, b: from_prompt(prompt, prompt_len, a, b))(prev, cur)
    if gen is None:
        return drafts

    T = gen.shape[1]
    gpos = jnp.arange(1, T)

    def from_gen(row, glen, a, b):
        # Exclude the row's TRAILING bigram itself (position glen-1): matching
        # it is vacuous and its continuation lies past the generated text.
        hit = (row[:-1] == a) & (row[1:] == b) & (gpos < glen - 1)
        last = jnp.max(jnp.where(hit, gpos, -1))
        idx = last + 1 + jnp.arange(k)
        ok = (last >= 0) & (idx < glen)
        return last >= 0, jnp.where(ok, row[jnp.clip(idx, 0, T - 1)], b).astype(jnp.int32)

    has_gen, gen_drafts = jax.vmap(from_gen)(gen, gen_len, prev, cur)
    return jnp.where(has_gen[:, None], gen_drafts, drafts)


def accept_drafts(
    sampled: jax.Array,
    drafts: jax.Array,
    eos_ids: jax.Array,
    budget: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Decide how many of the k+1 per-position draws can be emitted.

    sampled: [B, k+1] — position j's token drawn from p(. | prefix, drafts[:j]);
    drafts: [B, k]; eos_ids: [MAX_EOS] (-1 padded); budget: [B] remaining
    tokens the row may still emit (>= 1 for live rows).

    Position j+1's draw is only valid if every earlier draw matched its draft
    (else it was conditioned on a wrong prefix). Emission also stops AFTER the
    first eos and at the row's budget. Returns (emit_mask [B, k+1] bool,
    counts [B] int32 — tokens emitted, and hit_eos [B] bool).
    """
    B, k1 = sampled.shape
    k = k1 - 1
    matched = sampled[:, :k] == drafts  # draw j confirmed draft j+1's prefix
    chain = jnp.cumprod(matched.astype(jnp.int32), axis=1)
    # valid[j]: draw j was conditioned on an accepted prefix. valid[0] always.
    valid = jnp.concatenate([jnp.ones((B, 1), jnp.int32), chain], axis=1)

    is_eos = jnp.isin(sampled, eos_ids)
    # Emission stops after the first emitted eos: position j emits only if no
    # VALID eos occurred at an earlier position.
    eos_before = jnp.cumsum(jnp.where(valid.astype(bool) & is_eos, 1, 0), axis=1)
    no_eos_before = jnp.concatenate(
        [jnp.zeros((B, 1), jnp.int32), eos_before[:, :-1]], axis=1
    ) == 0

    within_budget = jnp.arange(k1)[None, :] < budget[:, None]
    emit = valid.astype(bool) & no_eos_before & within_budget
    counts = emit.sum(axis=1).astype(jnp.int32)
    hit_eos = jnp.any(emit & is_eos, axis=1)
    return emit, counts, hit_eos


def scatter_rows(buf: jax.Array, values: jax.Array, offsets: jax.Array) -> jax.Array:
    """Write ``values`` [B, W] into ``buf`` [B, T] at per-row ``offsets`` [B]
    (vmapped dynamic_update_slice; W is static, callers mask unused tail
    positions to values that are safe to write)."""
    return jax.vmap(
        lambda b, v, o: jax.lax.dynamic_update_slice_in_dim(b, v, o, axis=0)
    )(buf, values, offsets)


def scatter_rows_k(buf: jax.Array, values: jax.Array, offsets: jax.Array) -> jax.Array:
    """scatter_rows for per-position top-k payloads: buf [B, T, K],
    values [B, W, K], offsets [B] — the trailing top-k axis rides along."""
    return jax.vmap(
        lambda b, v, o: jax.lax.dynamic_update_slice(b, v, (o, 0))
    )(buf, values, offsets)
