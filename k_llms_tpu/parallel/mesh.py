"""Mesh construction.

Axes:
- ``data``: the n consensus samples (data parallel over ICI) — the TPU-native
  replacement for the reference's provider-side n fan-out
  (`/root/reference/k_llms/resources/completions/completions.py:70-73`).
- ``model``: tensor parallelism for weights that exceed one chip's HBM
  (Llama-3-8B bf16 = 16 GB = a whole v5e chip on its own).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    data: int,
    model: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if data * model > len(devices):
        raise ValueError(
            f"mesh {data}x{model} needs {data * model} devices, have {len(devices)}"
        )
    grid = np.array(devices[: data * model]).reshape(data, model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def auto_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    model_parallel: Optional[int] = None,
) -> Mesh:
    """Factorize the device count into (data, model).

    Default: all-model for big weights? No — consensus decoding is
    throughput-bound on the n samples, so default is all-data with
    ``model_parallel`` carved out only when requested (or set it to fit weights).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    mp = model_parallel or 1
    if n % mp != 0:
        raise ValueError(f"model_parallel={mp} does not divide device count {n}")
    return make_mesh(n // mp, mp, devices)
