"""Device-mesh parallelism.

The reference's only parallelism is provider-side sampling over one HTTP call
(SURVEY.md §2.3). Here parallelism is first-class: a (data, model) mesh where
the n consensus samples ride the data axis over ICI and the model weights are
tensor-parallel over the model axis; multi-host DCN via jax.distributed.
"""

from .mesh import DATA_AXIS, MODEL_AXIS, auto_mesh, make_mesh
from .sharding import batch_spec, cache_specs, param_specs

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "auto_mesh",
    "make_mesh",
    "param_specs",
    "cache_specs",
    "batch_spec",
]
