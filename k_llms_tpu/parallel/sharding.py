"""Partition specs for the Llama parameter/cache pytrees.

Megatron-style tensor parallelism laid out so every collective rides ICI:
column-parallel in-projections (wq/wk/wv/w_gate/w_up sharded on the output
feature axis), row-parallel out-projections (wo/w_down sharded on the input
feature axis) — GSPMD then inserts exactly one reduce per block. Embedding and
lm_head shard the vocab axis. Norms replicate. KV caches shard batch over
``data`` and kv-heads over ``model``.
"""

from __future__ import annotations

from typing import Any, Dict

from jax.sharding import PartitionSpec as P

from ..models.config import ModelConfig
from .mesh import DATA_AXIS, MODEL_AXIS


def param_specs(config: ModelConfig) -> Dict[str, Any]:
    """Pytree of PartitionSpec matching models.llama.init_params."""
    layers = {
        "attn_norm": P(None, None),
        "wq": P(None, None, MODEL_AXIS),
        "wk": P(None, None, MODEL_AXIS),
        "wv": P(None, None, MODEL_AXIS),
        "wo": P(None, MODEL_AXIS, None),
        "mlp_norm": P(None, None),
        "w_gate": P(None, None, MODEL_AXIS),
        "w_up": P(None, None, MODEL_AXIS),
        "w_down": P(None, MODEL_AXIS, None),
    }
    if config.num_experts > 0:
        # Expert parallelism: the expert axis of [L, E, H, I] weights shards
        # over "model"; each device computes its experts, GSPMD reduces the
        # combine. The router replicates.
        layers["w_router"] = P(None, None, None)
        layers["w_gate"] = P(None, MODEL_AXIS, None, None)
        layers["w_up"] = P(None, MODEL_AXIS, None, None)
        layers["w_down"] = P(None, MODEL_AXIS, None, None)
    if config.qkv_bias:
        # Biases follow their projection's output-feature sharding.
        layers["bq"] = P(None, MODEL_AXIS)
        layers["bk"] = P(None, MODEL_AXIS)
        layers["bv"] = P(None, MODEL_AXIS)
    if config.post_block_norms:
        layers["post_attn_norm"] = P(None, None)
        layers["post_mlp_norm"] = P(None, None)
    return {
        "embed": P(MODEL_AXIS, None),  # vocab-sharded
        "layers": layers,
        "final_norm": P(None),
        "lm_head": P(None, MODEL_AXIS),
    }


def cache_specs(shared_prefix: bool = False):
    """KV cache [L, B, S, KVH, D]: samples over data, kv heads over model.
    The shared prefix has batch 1, so only heads shard."""
    if shared_prefix:
        return P(None, None, None, MODEL_AXIS, None)
    return P(None, DATA_AXIS, None, MODEL_AXIS, None)


def batch_spec():
    """Per-sample vectors (tokens, logprobs, done flags): sharded over data."""
    return P(DATA_AXIS)
