"""Multi-host initialization (DCN process groups).

The reference's only transport is HTTPS to OpenAI (SURVEY.md §2.3). Here
multi-host scale-out uses JAX's distributed runtime: every host calls
``initialize_multihost`` before first device use; XLA then lays intra-slice
collectives on ICI and inter-host traffic on DCN automatically. No NCCL/MPI
analog is needed — the collectives in the sharded programs are the comms layer.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

logger = logging.getLogger(__name__)


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed from args or environment.

    Environment (matching JAX conventions / TPU pod metadata):
      KLLMS_COORDINATOR (host:port), KLLMS_NUM_PROCESSES, KLLMS_PROCESS_ID —
    falls back to jax.distributed's own auto-detection on TPU pods. Returns
    True if distributed mode was initialized, False for single-host runs.
    """
    coordinator_address = coordinator_address or os.getenv("KLLMS_COORDINATOR")
    num_processes = num_processes or _int_env("KLLMS_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _int_env("KLLMS_PROCESS_ID")

    if coordinator_address is None and num_processes is None:
        return False  # single host

    _enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "jax.distributed initialized: process %s/%s",
        jax.process_index(),
        jax.process_count(),
    )
    return True


def _enable_cpu_collectives() -> None:
    """Multi-process collectives on the CPU backend need an explicit CPU
    collectives implementation (gloo over TCP) — the default CPU client
    refuses cross-process computations outright. TPU/GPU have native
    collectives and never consult this flag, so only flip it when the
    selected platform is CPU. Must run before the backend initializes, hence
    the env/config sniff instead of jax.default_backend()."""
    platforms = os.getenv("JAX_PLATFORMS") or str(
        getattr(jax.config, "jax_platforms", None) or ""
    )
    if "cpu" not in platforms.lower().split(","):
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # jax build without the flag or the gloo impl
        logger.debug("CPU collectives implementation not configurable", exc_info=True)


def _int_env(name: str) -> Optional[int]:
    val = os.getenv(name)
    return int(val) if val else None


def global_mesh_devices():
    """All devices across processes (for building multi-host meshes)."""
    return jax.devices()
